"""Headline benchmark: RS k=8 m=3 encode GB/s on one TPU chip.

The driver runs this on real TPU hardware; it prints exactly ONE JSON
line. Config matches BASELINE.md row 2: RS k=8, m=3, 4 MiB stripe,
batched encode over 1024 objects (processed in device-sized sub-batches).
`vs_baseline` is measured GB/s divided by the 40 GB/s/chip north-star
target from BASELINE.json (no published reference number exists — see
BASELINE.md; >1.0 means the target is beaten).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_GBPS = 40.0
OBJECTS = 1024
OBJECT_SIZE = 4 * 1024 * 1024  # 4 MiB stripe
K, M = 8, 3


def main() -> None:
    import jax
    import numpy as np

    from ceph_tpu.ec.matrices import reed_sol_van_matrix
    from ceph_tpu.ops.rs_kernels import make_encoder

    matrix = reed_sol_van_matrix(K, M)
    chunk = OBJECT_SIZE // K  # 512 KiB, already 128-aligned

    # Sub-batch sized to keep data + parity + headroom well inside 16 GB
    # HBM; loop covers all 1024 objects per timed iteration.
    sub = min(int(os.environ.get("BENCH_SUBBATCH", "128")), OBJECTS)
    iters = max(1, OBJECTS // sub)
    objects_done = sub * iters
    rng = np.random.default_rng(0)
    host = rng.integers(0, 256, size=(sub, K, chunk), dtype=np.uint8)
    data = jax.device_put(host)

    results = {}
    impls = os.environ.get("BENCH_IMPLS", "bitlinear,mxu").split(",")
    for impl in impls:
        try:
            fn = make_encoder(matrix, impl)
            fn(data).block_until_ready()  # compile + warmup
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(data)
            out.block_until_ready()
            dt = time.perf_counter() - t0
            results[impl] = sub * K * chunk * iters / dt / 1e9
        except Exception as e:  # one impl failing shouldn't kill the bench
            print(f"bench: impl {impl} failed: {e!r}", file=sys.stderr)
    if not results:
        raise SystemExit("all bench impls failed")
    impl = max(results, key=results.get)
    gbps = results[impl]
    print(f"bench: {results} backend={jax.default_backend()}", file=sys.stderr)
    print(json.dumps({
        "metric": f"rs_k{K}m{M}_encode_4MiB_x{objects_done}",
        "value": round(gbps, 3),
        "unit": "GB/s/chip",
        "vs_baseline": round(gbps / TARGET_GBPS, 4),
    }))


if __name__ == "__main__":
    main()
