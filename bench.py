"""Headline benchmark: RS k=8 m=3 encode GB/s on one TPU chip.

Prints exactly ONE JSON line on stdout (driver contract); details land
on stderr. Methodology per docs/BENCH_METHODOLOGY.md — every guard
exists because round 1's naive loop reported a physically impossible
number (20 TB/s) on the axon tunnel platform:

* correctness gate: each timed kernel's full output for a small batch
  is fetched and byte-compared against the pure-numpy GF oracle before
  any timing; a wrong kernel aborts the bench.
* distinct inputs: a 4-batch pool of device-generated random data
  (`jax.random.bits`, no tunnel staging) is rotated every iteration.
* elision-proof sync: the whole timed loop is ONE jitted `lax.scan`
  whose carry XOR-folds a digest of every output; the clock stops when
  the scalar digest reaches the host, so the result data-depends on
  every encode and nothing can be dead-code-eliminated.
* slope timing: the pipeline runs at n1 and n2 iterations (both warmed,
  best of 3); throughput = bytes*(n2-n1)/(t2-t1), which cancels the
  constant dispatch+fetch latency of the tunnel (~70 ms RTT) without
  subtracting an unmeasured constant. Raw totals are printed so a
  skeptic can recompute.
* bytes accounting: the headline is INPUT bytes/s (k data shards), the
  convention of the reference's ceph_erasure_code_benchmark (object
  bytes / seconds; ref: src/test/erasure-code/
  ceph_erasure_code_benchmark.cc ErasureCodeBench::encode); touched
  bytes (k+m) are also reported.

The JSON line's `extra` dict carries the full metric set VERDICT r01
asked for: decode GB/s, every-impl encode table, CPU-native baseline,
CRUSH placement throughput, and recovery objects/s.

`vs_baseline` divides by the 40 GB/s/chip north-star target from
BASELINE.json (no published reference number exists — BASELINE.md).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_GBPS = 40.0
K, M = 8, 3
OBJECT_SIZE = 4 * 1024 * 1024          # 4 MiB object
CHUNK = OBJECT_SIZE // K               # 512 KiB chunk
SUB = int(os.environ.get("BENCH_SUBBATCH", "32"))   # objects per iteration
POOL = 4                               # rotated input batches
N1, N2 = 4, 20
REPS = 3


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def _pipeline(enc_fn, pool_arr):
    """One-jit scan: iteration i encodes pool[i%POOL]; carry is a u8
    XOR digest over every output byte (keeps all encodes live)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=1)
    def pipe(pool, n):
        def body(acc, i):
            x = jax.lax.dynamic_index_in_dim(pool, i % POOL, keepdims=False)
            out = enc_fn(x)
            d = jnp.bitwise_xor.reduce(
                jnp.bitwise_xor.reduce(out, axis=(0, 1)))
            return acc ^ d, None
        acc, _ = jax.lax.scan(body, jnp.uint8(0),
                              jnp.arange(n, dtype=jnp.int32))
        return acc
    return lambda n: int(jax.device_get(pipe(pool_arr, n)))


def _slope(run, bytes_per_iter):
    """Time run(N1) and run(N2) (warmed, best of REPS); return
    (GB/s, t1, t2). If jitter leaves no usable slope (t2 <= t1), fall
    back to the latency-inclusive rate bytes*N2/t2 — a strict lower
    bound on real throughput — rather than publishing a negative or
    inflated number."""
    for n in (N1, N2):
        run(n)  # compile + warm both program sizes
    t1 = min(_timed(run, N1) for _ in range(REPS))
    t2 = min(_timed(run, N2) for _ in range(REPS))
    if t2 > t1 * 1.02:
        gbps = bytes_per_iter * (N2 - N1) / (t2 - t1) / 1e9
    else:
        gbps = bytes_per_iter * N2 / t2 / 1e9
        log(f"slope unusable (t1={t1:.3f}s t2={t2:.3f}s); reporting "
            f"latency-inclusive lower bound")
    return gbps, t1, t2


def _timed(run, n):
    t0 = time.perf_counter()
    run(n)
    return time.perf_counter() - t0


def bench_encode_impls(impls):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ceph_tpu.ec.matrices import reed_sol_van_matrix
    from ceph_tpu.gf.numpy_ref import encode_ref
    from ceph_tpu.ops.rs_kernels import make_encoder

    matrix = reed_sol_van_matrix(K, M)

    # correctness gate (small batch, full fetch, oracle compare)
    rng = np.random.default_rng(11)
    small = rng.integers(0, 256, size=(2, K, 8192), dtype=np.uint8)
    want = np.stack([encode_ref(matrix, small[b]) for b in range(2)])

    pool = jax.jit(
        lambda key: jax.random.bits(key, (POOL, SUB, K, CHUNK), jnp.uint8)
    )(jax.random.key(7))
    pool.block_until_ready()
    bytes_per_iter = SUB * K * CHUNK

    results = {}
    for impl in impls:
        try:
            fn = make_encoder(matrix, impl, bucket_batch=False)
            got = np.asarray(fn(small))
            if not (got == want).all():
                raise AssertionError(f"impl {impl} output != oracle")
            run = _pipeline(fn, pool)
            gbps, t1, t2 = _slope(run, bytes_per_iter)
            results[impl] = gbps
            log(f"encode {impl}: t({N1})={t1:.3f}s t({N2})={t2:.3f}s "
                f"slope {gbps:.2f} GB/s in "
                f"({bytes_per_iter * (N2 - N1) / 1e9:.2f} GB marginal, "
                f"touched x{(K + M) / K:.3f})")
        except AssertionError:
            raise  # wrong bytes must kill the bench, not be skipped
        except Exception as e:
            log(f"encode impl {impl} failed: {e!r}")
    return results


def bench_decode():
    """Degraded-read decode: rebuild 2 erased shards from k survivors
    (erasures {0, 9}), static decode matrix — the ErasureCodeBench
    --workload decode analog."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ceph_tpu.ec.matrices import reed_sol_van_matrix
    from ceph_tpu.gf.numpy_ref import decode_matrix, encode_ref
    from ceph_tpu.ops.rs_kernels import make_encoder

    matrix = reed_sol_van_matrix(K, M)
    erasures = [0, K + 1]
    survivors = [i for i in range(K + M) if i not in erasures][:K]
    D = decode_matrix(matrix, erasures, K, survivors)

    # gate: decode oracle-encoded survivors, compare rebuilt shards
    rng = np.random.default_rng(12)
    small = rng.integers(0, 256, size=(2, K, 8192), dtype=np.uint8)
    fn = make_encoder(D, "mxu", bucket_batch=False)
    full = [np.concatenate([small[b], encode_ref(matrix, small[b])], axis=0)
            for b in range(2)]
    surv = np.stack([f[survivors] for f in full])
    want = np.stack([f[erasures] for f in full])
    got = np.asarray(fn(surv))
    if not (got == want).all():
        raise AssertionError("decode output != oracle")

    pool = jax.jit(
        lambda key: jax.random.bits(key, (POOL, SUB, K, CHUNK), jnp.uint8)
    )(jax.random.key(8))
    pool.block_until_ready()
    run = _pipeline(fn, pool)
    bytes_per_iter = SUB * K * CHUNK  # k survivor chunks read per object
    gbps, t1, t2 = _slope(run, bytes_per_iter)
    log(f"decode mxu (2 erasures): t({N1})={t1:.3f}s t({N2})={t2:.3f}s "
        f"slope {gbps:.2f} GB/s in")
    return gbps


def bench_cpu_native():
    """CPU baseline via the native codec (BASELINE.md rows 1-2)."""
    import numpy as np
    out = {}
    try:
        import ceph_tpu.native  # noqa: F401 — registers the plugin
        from ceph_tpu.ec.registry import factory
        for kk, mm, size, label in ((4, 2, 1 << 20, "k4m2_1MiB"),
                                    (K, M, OBJECT_SIZE, "k8m3_4MiB")):
            coder = factory(f"plugin=native k={kk} m={mm}")
            rng = np.random.default_rng(5)
            batch = max(1, (64 << 20) // size)
            data = rng.integers(0, 256, (batch, kk, size // kk), np.uint8)
            coder.encode_chunks(data)  # warm table init
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                coder.encode_chunks(data)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            gbps = batch * size / best / 1e9
            out[label] = round(gbps, 3)
            log(f"cpu native encode {label}: {gbps:.2f} GB/s/core")
    except Exception as e:
        log(f"cpu native baseline failed: {e!r}")
    return out


def bench_crush(n_objects=int(os.environ.get("BENCH_CRUSH_OBJECTS",
                                             "1000000")),
                n_osds=10_000):
    """BASELINE config #5 geometry: place n_objects PGs on an
    n_osds-OSD CRUSH map (EC rule, indep), vectorized mapper. The full
    10M run is config #5 verbatim; the default 1M keeps the driver
    bench under budget and the rate extrapolates linearly (per-lane
    cost is batch-independent — measured)."""
    import numpy as np

    from ceph_tpu.crush.map import build_hierarchy, ec_rule
    from ceph_tpu.crush.mapper import VectorMapper, full_weights

    try:
        m = build_hierarchy(n_osds, osds_per_host=10, hosts_per_rack=25)
        ec_rule(m, rule_id=1, choose_type=1)
        vm = VectorMapper(m)
        weights = full_weights(n_osds)
        sub = 1_000_000
        xs0 = np.arange(sub, dtype=np.uint32)
        np.asarray(vm.do_rule(1, xs0, weights, K + M))  # compile + warm
        t0 = time.perf_counter()
        done = 0
        # full sub-batches only (variable tails would recompile); the
        # rate divides by the count actually placed
        while done < n_objects:
            xs = np.arange(done, done + sub, dtype=np.uint32)
            res = vm.do_rule(1, xs, weights, K + M)
            done += sub
        np.asarray(res)  # sync on the last batch
        dt = time.perf_counter() - t0
        rate = done / dt
        log(f"crush: {done} placements x{K + M} on {n_osds} OSDs "
            f"in {dt:.2f}s = {rate / 1e6:.2f} M placements/s")
        return rate
    except Exception as e:
        log(f"crush bench failed: {e!r}")
        return None


def bench_recovery(objects=128, size=1 << 20, lost=1):
    """PG recovery objects/s through the mini-ECBackend (metric #2)."""
    import numpy as np
    try:
        from ceph_tpu.ec.interface import profile_from_string
        from ceph_tpu.osd.ecbackend import ECBackend, ShardSet

        profile = profile_from_string(f"k={K} m={M}")
        cluster = ShardSet()
        be = ECBackend(profile, "1.0", list(range(K + M)), cluster)
        rng = np.random.default_rng(0)
        objs = {f"obj{i:06d}": rng.integers(0, 256, size, np.uint8)
                for i in range(objects)}
        be.write_objects(objs)
        dead = list(range(lost))
        for s in dead:
            cluster.stores.pop(be.acting[s], None)
        repl = {s: 1000 + s for s in dead}
        t0 = time.perf_counter()
        counters = be.recover_shards(dead, replacement_osds=repl)
        dt = time.perf_counter() - t0
        rate = objects / dt
        log(f"recovery: {counters['bytes'] >> 20} MiB rebuilt over "
            f"{objects} x {size >> 20} MiB objects in {dt:.2f}s = "
            f"{rate:.1f} objects/s")
        return rate
    except Exception as e:
        log(f"recovery bench failed: {e!r}")
        return None


def main() -> None:
    import jax
    log(f"backend={jax.default_backend()} devices={jax.devices()}")

    impls = os.environ.get("BENCH_IMPLS", "mxu,bitlinear,pallas").split(",")
    enc = bench_encode_impls([i for i in impls if i])
    if not enc:
        raise SystemExit("all encode impls failed")
    extra = {"encode_gbps_by_impl": {k: round(v, 3) for k, v in enc.items()}}

    skip = set(os.environ.get("BENCH_SKIP", "").split(","))
    if "decode" not in skip:
        try:
            extra["decode_gbps"] = round(bench_decode(), 3)
        except Exception as e:
            log(f"decode bench failed: {e!r}")
    if "cpu" not in skip:
        extra["cpu_native_encode_gbps"] = bench_cpu_native()
    if "crush" not in skip:
        r = bench_crush()
        if r:
            extra["crush_placements_per_s"] = round(r)
    if "recovery" not in skip:
        r = bench_recovery()
        if r:
            extra["recovery_objects_per_s"] = round(r, 1)

    impl = max(enc, key=enc.get)
    gbps = enc[impl]
    extra["best_impl"] = impl
    extra["methodology"] = "slope-timed scan pipeline, digest-synced, " \
        "oracle-gated (docs/BENCH_METHODOLOGY.md)"
    print(json.dumps({
        "metric": f"rs_k{K}m{M}_encode_4MiB_input",
        "value": round(gbps, 3),
        "unit": "GB/s/chip",
        "vs_baseline": round(gbps / TARGET_GBPS, 4),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
