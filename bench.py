"""Headline benchmark: RS k=8 m=3 encode GB/s on one TPU chip.

Prints exactly ONE JSON line on stdout (driver contract); details land
on stderr. Methodology per docs/BENCH_METHODOLOGY.md — every guard
exists because round 1's naive loop reported a physically impossible
number (20 TB/s) on the axon tunnel platform:

* correctness gate: each timed kernel's full output for a small batch
  is fetched and byte-compared against the pure-numpy GF oracle before
  any timing; a wrong kernel aborts that impl's bench.
* distinct inputs: a 4-batch pool of device-generated random data
  (`jax.random.bits`, no tunnel staging) is rotated every iteration.
* elision-proof sync: the whole timed loop is ONE jitted `lax.scan`
  whose carry XOR-folds a digest of every output; the clock stops when
  the scalar digest reaches the host, so the result data-depends on
  every encode and nothing can be dead-code-eliminated.
* slope timing: the pipeline runs at n1 and n2 iterations (both warmed,
  best of 3); throughput = bytes*(n2-n1)/(t2-t1), which cancels the
  constant dispatch+fetch latency of the tunnel (~70 ms RTT) without
  subtracting an unmeasured constant. Raw totals are printed so a
  skeptic can recompute.
* bytes accounting: the headline is INPUT bytes/s (k data shards), the
  convention of the reference's ceph_erasure_code_benchmark (object
  bytes / seconds; ref: src/test/erasure-code/
  ceph_erasure_code_benchmark.cc ErasureCodeBench::encode); touched
  bytes (k+m) are also reported.

Availability engineering (round 3 — the tunnel was sick for the whole
of rounds 1-2 and the driver gets exactly ONE run per round):

* backend acquisition happens in SUBPROCESSES with hard timeouts — the
  known failure mode is a jax.devices() call that hangs forever, which
  no in-process try/except can survive. Probes retry with exponential
  backoff for up to BENCH_TPU_WAIT seconds (default 600).
* if the chip never comes up, the bench falls back to the CPU backend
  (jax.config.update wins over the site hook's axon selection), runs
  every section that is still meaningful, and reports
  `extra.tpu_ok: false` plus the probe diagnostics.
* a watchdog thread flushes whatever has been measured as the one JSON
  line and hard-exits at BENCH_DEADLINE seconds (default 1800), so a
  MID-RUN hang also cannot produce an empty artifact.
* results land in a shared STATE dict the moment they are measured;
  the final line is assembled from STATE by whoever emits first
  (normal path or watchdog), guarded by an Event.

The JSON line's `extra` dict carries the full metric set: decode GB/s,
every-impl encode table (incl. pallas), CPU-native baseline, CRUSH
placement throughput, recovery objects/s + GB/s at the 4 MiB/2-loss
north-star geometry, and LRC/Clay single-chunk repair (GB/s + measured
helper-I/O ratios — BASELINE rows 3 and 4).

`vs_baseline` divides by the 40 GB/s/chip north-star target from
BASELINE.json (no published reference number exists — BASELINE.md).
"""

import functools
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_GBPS = 40.0
K, M = 8, 3
OBJECT_SIZE = 4 * 1024 * 1024          # 4 MiB object
CHUNK = OBJECT_SIZE // K               # 512 KiB chunk
SUB = int(os.environ.get("BENCH_SUBBATCH", "32"))   # objects per iteration
POOL = 4                               # rotated input batches
N1, N2 = 4, 20
REPS = 3
TPU_WAIT = float(os.environ.get("BENCH_TPU_WAIT", "600"))
DEADLINE = float(os.environ.get("BENCH_DEADLINE", "1800"))

T0 = time.monotonic()
STATE = {"extra": {}, "errors": [], "backend": None, "tpu_ok": False}
_EMITTED = threading.Event()       # wakes the watchdog's sleep
_EMIT_LOCK = threading.Lock()      # serializes the one emission
# Child mode (round 5): the 03:17Z r4 capture lost crush AND wedged the
# tunnel for everything after it when the TPU worker crashed mid-section.
# Risky sections therefore run in SUBPROCESSES with their own JAX client:
# a worker crash kills only the child; the parent retries with a fresh
# client (and a smaller working set) inside the same live window.
CHILD_SECTION = os.environ.get("BENCH_SECTION_ONLY") or None


def log(msg: str) -> None:
    print(f"bench[{time.monotonic() - T0:7.1f}s]: {msg}",
          file=sys.stderr, flush=True)


def fail(where: str, err) -> None:
    msg = f"{where}: {err!r}"
    log(msg)
    STATE["errors"].append(msg[:300])


def _snapshot_state() -> dict:
    """Deep-copy STATE tolerating concurrent inserts from the main
    thread (the watchdog emits while sections may still be running)."""
    import copy
    for _ in range(5):
        try:
            return copy.deepcopy(STATE)
        except RuntimeError:       # "dictionary changed size..."
            time.sleep(0.05)
    return {"extra": {}, "errors": STATE["errors"][:],
            "backend": STATE["backend"], "tpu_ok": STATE["tpu_ok"]}


def emit(note: str | None = None) -> None:
    """Assemble and print THE one JSON line from STATE. Exactly-once:
    lock + flag (an Event alone would be check-then-set racy between
    the watchdog and the normal path)."""
    with _EMIT_LOCK:
        if _EMITTED.is_set():
            return
        _EMITTED.set()
        snap = _snapshot_state()
    if CHILD_SECTION:
        # child-mode line: consumed by the parent bench, not the driver
        print(json.dumps({
            "child": CHILD_SECTION,
            "tpu_ok": snap["tpu_ok"],
            "backend": snap["backend"],
            "extra": snap["extra"],
            "errors": snap["errors"],
            "note": note,
        }), flush=True)
        return
    extra = snap["extra"]
    enc = extra.get("encode_gbps_by_impl") or {}
    ok = bool(enc) and snap["tpu_ok"]
    if enc:
        impl = max(enc, key=enc.get)
        gbps = enc[impl]
        extra["best_impl"] = impl
    else:
        gbps = 0.0
    extra["ok"] = ok
    extra["backend"] = snap["backend"]
    extra["tpu_ok"] = snap["tpu_ok"]
    extra["elapsed_s"] = round(time.monotonic() - T0, 1)
    prov = os.environ.get("BENCH_PROVENANCE")
    if prov:
        extra["provenance"] = prov
    try:
        # the full config #5 run is recorded once by tools/crush_10m.py
        # (it takes ~an hour on the CPU fallback — far past this
        # harness's deadline); fold it in so the artifact carries the
        # measured-not-extrapolated figure
        with open(os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "CRUSH_10M.json")) as f:
            extra["crush_10m"] = json.load(f)
    except (OSError, ValueError):
        pass
    if not snap["tpu_ok"]:
        # the tunnel was down for this run: merge the last good
        # mid-round TPU capture (tools/tpu_probe.py commits it the
        # moment a probe succeeds) so a round-end dead tunnel doesn't
        # erase TPU evidence gathered hours earlier. Clearly labeled
        # as cached — the headline stays the LIVE measurement.
        try:
            cache = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "BENCH_mid.json")
            with open(cache) as f:
                cached = json.load(f)
            if cached.get("extra", {}).get("tpu_ok"):
                extra["cached_tpu"] = {
                    "metric": cached.get("metric"),
                    "value": cached.get("value"),
                    "provenance": cached["extra"].get(
                        "provenance", "mid-round capture"),
                    "encode_gbps_by_impl": cached["extra"].get(
                        "encode_gbps_by_impl"),
                    "decode_gbps_by_impl": cached["extra"].get(
                        "decode_gbps_by_impl"),
                }
        except (OSError, ValueError, KeyError):
            pass
    if note:
        extra["note"] = note
    if snap["errors"]:
        extra["errors"] = snap["errors"][:8]
    extra["methodology"] = "slope-timed scan pipeline, digest-synced, " \
        "oracle-gated (docs/BENCH_METHODOLOGY.md)"
    print(json.dumps({
        "metric": f"rs_k{K}m{M}_encode_4MiB_input",
        "value": round(gbps, 3),
        "unit": "GB/s/chip",
        "vs_baseline": round(gbps / TARGET_GBPS, 4),
        "extra": extra,
    }), flush=True)


def _watchdog() -> None:
    def run():
        budget = DEADLINE - (time.monotonic() - T0) - 5.0
        if _EMITTED.wait(timeout=max(budget, 1.0)):
            return
        try:
            log(f"WATCHDOG: deadline {DEADLINE}s reached; flushing "
                f"partial results and exiting")
            STATE["errors"].append(
                "watchdog: deadline hit, partial results")
            emit(note="watchdog flush")
            sys.stderr.flush()
        except BaseException as e:     # noqa: BLE001 — last resort:
            try:                       # the line MUST still print
                print(json.dumps({
                    "metric": f"rs_k{K}m{M}_encode_4MiB_input",
                    "value": 0.0, "unit": "GB/s/chip",
                    "vs_baseline": 0.0,
                    "extra": {"ok": False,
                              "note": f"watchdog emergency: {e!r}"},
                }), flush=True)
            except BaseException:      # noqa: BLE001
                pass
        finally:
            os._exit(0)
    threading.Thread(target=run, daemon=True).start()


# -- backend acquisition ----------------------------------------------------

_PROBE_SRC = """\
import jax, jax.numpy as jnp
ds = jax.devices()
v = int(jax.jit(lambda x: x + 1)(jnp.int32(41)))
assert v == 42, v
print("PLATFORM=" + ds[0].platform, flush=True)
"""


def _probe(timeout: float) -> tuple[str | None, str | None]:
    """Probe backend setup AND a tiny jit compile in a subprocess (the
    known failure mode is a hang no in-process guard survives). The
    child runs in its OWN PROCESS GROUP and a hang kills the whole
    group — plain subprocess timeout kills only the direct child, and
    a wedged tunnel grandchild kept the fd open so communicate() still
    blocked (the r05 capture lost 600 s to four silent 150 s stalls).
    Returns (platform, cause) — exactly one is None."""
    import signal
    try:
        p = subprocess.Popen([sys.executable, "-c", _PROBE_SRC],
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             start_new_session=True)
    except Exception as e:        # noqa: BLE001 — diagnostics, not control
        fail("probe", e)
        return None, f"spawn failed: {e!r}"[:160]
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:         # noqa: BLE001 — already killed
            pass
        cause = f"hung > {timeout:.0f}s (process group killed)"
        fail("probe", cause)
        return None, cause
    for line in out.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    tail = " | ".join((err or "").strip().splitlines()[-3:])[:200]
    cause = f"rc={p.returncode} stderr={tail}"
    fail("probe", cause)
    return None, cause


def acquire_backend() -> str:
    """Patiently wait for the TPU tunnel; fall back to CPU. Returns the
    platform this process should use ('axon'/'tpu'/'cpu'/...). No jax
    import happens in this process until the decision is made. Probe
    outcomes land in extra.tpu_probe (attempts, per-attempt causes,
    wall spent) so a dead tunnel reads as one JSON line instead of a
    silent stall; after two consecutive HANGS the wait is cut short —
    a wedged tunnel does not un-wedge within one bench window (r04/r05
    evidence), and every further 150 s probe starves the real
    sections."""
    t_probe0 = time.monotonic()
    diag = STATE["extra"].setdefault(
        "tpu_probe", {"attempts": 0, "causes": []})

    def _record(plat: str | None, cause: str | None) -> None:
        diag["attempts"] += 1
        if cause:
            diag["causes"].append(cause[:160])
        diag["outcome"] = plat or "cpu-fallback"
        diag["wall_s"] = round(time.monotonic() - t_probe0, 1)

    want_tpu = bool(os.environ.get("PALLAS_AXON_POOL_IPS")) and \
        os.environ.get("JAX_PLATFORMS", "") != "cpu"
    if not want_tpu:
        plat, cause = _probe(timeout=180)
        _record(plat, cause)
        plat = plat or "cpu"
        log(f"no TPU tunnel configured; backend={plat}")
        return plat
    probe_deadline = time.monotonic() + min(TPU_WAIT, DEADLINE * 0.45)
    delay, attempt, hangs = 5.0, 0, 0
    while time.monotonic() < probe_deadline:
        attempt += 1
        left = probe_deadline - time.monotonic()
        # hard per-probe deadline: full patience for the first try,
        # but once a probe has HUNG (vs failed fast) shrink the
        # follow-ups — they are confirming a wedge, not waiting out
        # a boot
        per_probe = max(60.0, min(150.0, left)) if hangs == 0 \
            else max(45.0, min(60.0, left))
        log(f"TPU probe #{attempt} (timeout {per_probe:.0f}s, "
            f"{left:.0f}s of patience left)")
        plat, cause = _probe(timeout=per_probe)
        _record(plat, cause)
        if plat:
            log(f"TPU probe #{attempt} OK: platform={plat}")
            return plat
        if cause and cause.startswith("hung"):
            hangs += 1
            if hangs >= 2:
                diag["outcome"] = "cpu-fallback (tunnel wedged)"
                log("two consecutive probe hangs: tunnel presumed "
                    "wedged; falling back to CPU early")
                return "cpu"
        else:
            hangs = 0
        if time.monotonic() + delay >= probe_deadline:
            break
        time.sleep(delay)
        delay = min(delay * 2, 120.0)
    log(f"TPU never came up after {attempt} probes; falling back to CPU "
        f"(CPU sections still run; tpu_ok=false)")
    return "cpu"


def _force_cpu() -> None:
    """Make THIS process use the CPU backend even though the site hook
    selected axon at startup: an explicit jax.config update outranks
    both the hook and JAX_PLATFORMS (same trick as tests/conftest.py)."""
    import jax
    jax.config.update("jax_platforms", "cpu")


# -- timed pipeline ---------------------------------------------------------

def _device_pool(shape, seed):
    """Device-generated rotating input pool (no tunnel staging): the
    one shared setup for every slope-timed section."""
    import jax
    import jax.numpy as jnp
    pool = jax.jit(
        lambda key: jax.random.bits(key, (POOL,) + tuple(shape),
                                    jnp.uint8))(jax.random.key(seed))
    pool.block_until_ready()
    return pool


def _pipeline(enc_fn, pool_arr):
    """One-jit scan: iteration i encodes pool[i%POOL]; carry is a u8
    XOR digest over every output byte (keeps all encodes live)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=1)
    def pipe(pool, n):
        def body(acc, i):
            x = jax.lax.dynamic_index_in_dim(pool, i % POOL, keepdims=False)
            out = enc_fn(x)
            d = jnp.bitwise_xor.reduce(
                jnp.bitwise_xor.reduce(out, axis=(0, 1)))
            return acc ^ d, None
        acc, _ = jax.lax.scan(body, jnp.uint8(0),
                              jnp.arange(n, dtype=jnp.int32))
        return acc
    return lambda n: int(jax.device_get(pipe(pool_arr, n)))


def _slope(run, bytes_per_iter, n1=None, n2=None, reps=None):
    """Time run(n1) and run(n2) (warmed, best of reps); return
    (GB/s, t1, t2). If jitter leaves no usable slope (t2 <= t1), fall
    back to the latency-inclusive rate bytes*n2/t2 — a strict lower
    bound on real throughput — rather than publishing a negative or
    inflated number. (bytes_per_iter may be any unit — bench_crush
    passes placements and scales the returned "GB/s" by 1e9.)"""
    n1 = N1 if n1 is None else n1
    n2 = N2 if n2 is None else n2
    reps = REPS if reps is None else reps
    for n in (n1, n2):
        run(n)  # compile + warm both program sizes
    t1 = min(_timed(run, n1) for _ in range(reps))
    t2 = min(_timed(run, n2) for _ in range(reps))
    if t2 > t1 * 1.02:
        gbps = bytes_per_iter * (n2 - n1) / (t2 - t1) / 1e9
    else:
        gbps = bytes_per_iter * n2 / t2 / 1e9
        log(f"slope unusable (t1={t1:.3f}s t2={t2:.3f}s); reporting "
            f"latency-inclusive lower bound")
    return gbps, t1, t2


def _timed(run, n):
    t0 = time.perf_counter()
    run(n)
    return time.perf_counter() - t0


# -- sections ---------------------------------------------------------------

def bench_encode_impls(impls):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ceph_tpu.ec.matrices import reed_sol_van_matrix
    from ceph_tpu.gf.numpy_ref import encode_ref
    from ceph_tpu.ops.rs_kernels import make_encoder

    matrix = reed_sol_van_matrix(K, M)

    # correctness gate (small batch, full fetch, oracle compare)
    rng = np.random.default_rng(11)
    small = rng.integers(0, 256, size=(2, K, 8192), dtype=np.uint8)
    want = np.stack([encode_ref(matrix, small[b]) for b in range(2)])

    pool = _device_pool((SUB, K, CHUNK), 7)
    bytes_per_iter = SUB * K * CHUNK

    results = STATE["extra"].setdefault("encode_gbps_by_impl", {})
    for impl in impls:
        try:
            fn = make_encoder(matrix, impl, bucket_batch=False)
            got = np.asarray(fn(small))
            if not (got == want).all():
                raise AssertionError(f"impl {impl} output != oracle")
            run = _pipeline(fn, pool)
            gbps, t1, t2 = _slope(run, bytes_per_iter)
            results[impl] = round(gbps, 3)
            log(f"encode {impl}: t({N1})={t1:.3f}s t({N2})={t2:.3f}s "
                f"slope {gbps:.2f} GB/s in "
                f"({bytes_per_iter * (N2 - N1) / 1e9:.2f} GB marginal, "
                f"touched x{(K + M) / K:.3f})")
        except Exception as e:    # noqa: BLE001 — isolate per impl
            fail(f"encode impl {impl}", e)
    return results


def bench_decode(impls):
    """Degraded-read decode: rebuild 2 erased shards from k survivors
    (erasures {0, 9}), static decode matrix — the ErasureCodeBench
    --workload decode analog. Scans every impl exactly like encode
    (decode IS the same GF matmul after submatrix inversion — r3's
    mxu-pinned number recorded the slowest lowering as "decode");
    `decode_gbps` is the best impl's slope."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ceph_tpu.ec.matrices import reed_sol_van_matrix
    from ceph_tpu.gf.numpy_ref import decode_matrix, encode_ref
    from ceph_tpu.ops.rs_kernels import make_encoder

    matrix = reed_sol_van_matrix(K, M)
    erasures = [0, K + 1]
    survivors = [i for i in range(K + M) if i not in erasures][:K]
    D = decode_matrix(matrix, erasures, K, survivors)

    # gate inputs: oracle-encoded survivors and expected rebuilt shards
    rng = np.random.default_rng(12)
    small = rng.integers(0, 256, size=(2, K, 8192), dtype=np.uint8)
    full = [np.concatenate([small[b], encode_ref(matrix, small[b])], axis=0)
            for b in range(2)]
    surv = np.stack([f[survivors] for f in full])
    want = np.stack([f[erasures] for f in full])

    pool = _device_pool((SUB, K, CHUNK), 8)
    bytes_per_iter = SUB * K * CHUNK  # k survivor chunks read per object

    results = STATE["extra"].setdefault("decode_gbps_by_impl", {})
    for impl in impls:
        try:
            fn = make_encoder(D, impl, bucket_batch=False)
            got = np.asarray(fn(surv))
            if not (got == want).all():
                raise AssertionError(f"impl {impl} decode != oracle")
            run = _pipeline(fn, pool)
            gbps, t1, t2 = _slope(run, bytes_per_iter)
            results[impl] = round(gbps, 3)
            log(f"decode {impl} (2 erasures): t({N1})={t1:.3f}s "
                f"t({N2})={t2:.3f}s slope {gbps:.2f} GB/s in")
        except Exception as e:    # noqa: BLE001 — isolate per impl
            fail(f"decode impl {impl}", e)
    if results:
        best = max(results, key=results.get)
        STATE["extra"]["decode_gbps"] = results[best]
        STATE["extra"]["decode_best_impl"] = best
    return results


def bench_cpu_native():
    """CPU baseline via the native codec (BASELINE.md rows 1-2)."""
    import numpy as np
    out = STATE["extra"].setdefault("cpu_native_encode_gbps", {})
    try:
        import ceph_tpu.native  # noqa: F401 — registers the plugin
        from ceph_tpu.ec.registry import factory
        for kk, mm, size, label in ((4, 2, 1 << 20, "k4m2_1MiB"),
                                    (K, M, OBJECT_SIZE, "k8m3_4MiB")):
            coder = factory(f"plugin=native k={kk} m={mm}")
            rng = np.random.default_rng(5)
            batch = max(1, (64 << 20) // size)
            data = rng.integers(0, 256, (batch, kk, size // kk), np.uint8)
            coder.encode_chunks(data)  # warm table init
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                coder.encode_chunks(data)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            gbps = batch * size / best / 1e9
            out[label] = round(gbps, 3)
            log(f"cpu native encode {label}: {gbps:.2f} GB/s/core")
    except Exception as e:        # noqa: BLE001 — section isolation
        fail("cpu native baseline", e)
    return out


def bench_crush(n_objects=int(os.environ.get("BENCH_CRUSH_OBJECTS",
                                             "1000000")),
                n_osds=10_000):
    """BASELINE config #5 geometry: place PGs on an n_osds-OSD CRUSH
    map (EC rule, indep), vectorized mapper. The rate is a slope over
    two scan sizes whose larger leg places ~n_objects
    (BENCH_CRUSH_OBJECTS trims/extends it); the verbatim 10M run is
    appended when the measured rate fits the deadline."""
    from ceph_tpu.crush.map import build_hierarchy, ec_rule
    from ceph_tpu.crush.mapper import VectorMapper, full_weights

    m = build_hierarchy(n_osds, osds_per_host=10, hosts_per_rack=25)
    ec_rule(m, rule_id=1, choose_type=1)
    weights = full_weights(n_osds)
    # Lane sizing: the 1M-lane sub-batch crashed the TPU worker in both
    # live captures ("kernel fault" — working-set pressure from the
    # unrolled descend x numrep loop body); 10k lanes ran the full 10M
    # on the chip with no crash (tools/crush_10m.py, 2026-07-31). The
    # whole batch loop runs inside ONE jitted lax.scan with
    # device-generated seeds and an XOR digest carry (scan_rule):
    # per-dispatch tunnel RTT (~2s observed) otherwise dominates.
    sub = int(os.environ.get("BENCH_CRUSH_SUB", "10000"))
    if STATE["tpu_ok"]:
        nb2 = max(20, min(1000, n_objects // sub))
    else:
        nb2 = max(4, min(10, n_objects // sub))
    nb1 = max(1, nb2 // 10)

    while True:
        try:
            vm = VectorMapper(m)
            run = lambda nb: vm.scan_rule(1, weights, K + M, 0, sub, nb)
            rate, t1, t2 = _slope(run, sub * 1e9, n1=nb1, n2=nb2,
                                  reps=2)   # *1e9: units are placements
            break
        except Exception as e:    # noqa: BLE001 — retry ladder
            if not STATE["tpu_ok"] or sub <= 2_500:
                raise
            log(f"crush: sub-batch {sub} failed ({type(e).__name__}); "
                f"halving and retrying")
            sub //= 2
            time.sleep(20.0)      # give a restarted worker time to boot
    log(f"crush: slope over {sub * (nb2 - nb1)} placements x{K + M} on "
        f"{n_osds} OSDs (t({nb1})={t1:.2f}s t({nb2})={t2:.2f}s) = "
        f"{rate / 1e6:.2f} M placements/s")
    STATE["extra"]["crush_placements_per_s"] = round(rate)
    STATE["extra"]["crush_config"] = {
        "sub": sub, "n_batches": nb2, "n_osds": n_osds,
        "numrep": K + M}
    # BASELINE config #5 is 10M objects verbatim: run it in full when
    # the measured rate says it fits the deadline comfortably
    full = 10_000_000
    if full / rate < 150:
        t0 = time.perf_counter()
        done = 0
        while done < full:
            vm.scan_rule(1, weights, K + M, done, sub, nb2)
            done += sub * nb2
        dt = time.perf_counter() - t0
        log(f"crush full config#5: {done} placements in {dt:.2f}s = "
            f"{done / dt / 1e6:.2f} M placements/s (incl. "
            f"{done // (sub * nb2)} dispatch RTTs)")
        STATE["extra"]["crush_placements_per_s_10M"] = round(done / dt)
    return rate


def bench_recovery(objects=int(os.environ.get("BENCH_RECOVERY_OBJECTS",
                                              "128")),
                   size=OBJECT_SIZE, lost=2):
    """PG recovery at the north-star geometry: 4 MiB objects, TWO lost
    shards, rebuilt through ECBackend's fused CRC+decode+CRC pipeline
    (ref: src/osd/ECBackend.cc continue_recovery_op). Two numbers:

    * device-resident slope of the SAME fused program recovery
      launches (helper-CRC verify + decode + rebuilt-CRC), pipelined
      in one lax.scan dispatch — the kernel rate, free of tunnel
      staging (the r3/r4.0 captures measured ~2s of tunnel RTT per
      launch, not the kernel);
    * the end-to-end host path through ECBackend/ShardSet staging,
      kept as the honesty lower bound.

    Fused batch: the dec+CRC program at B>=32 CRASHES the axon remote
    compile helper (HTTP 500; the tunnel then wedges — every later
    compile hangs. Bisect 2026-07-31, BENCH_METHODOLOGY "round-4
    capture findings"). B=4 compiles in ~70s and runs; stay small and
    pipeline more launches instead."""
    import numpy as np
    from ceph_tpu.ec.interface import profile_from_string
    from ceph_tpu.osd.ecbackend import ECBackend, ShardSet

    fused_env = os.environ.get("BENCH_RECOVERY_BATCH")
    if not STATE["tpu_ok"]:
        objects = min(objects, 32)   # CPU fallback: stay in deadline
        fused_b = int(fused_env or 32)   # no remote helper to crash
    else:
        fused_b = int(fused_env or 4)
    profile = profile_from_string(f"k={K} m={M}")
    cluster = ShardSet()
    be = ECBackend(profile, "1.0", list(range(K + M)), cluster)
    rng = np.random.default_rng(0)
    objs = {f"obj{i:06d}": rng.integers(0, 256, size, np.uint8)
            for i in range(objects)}
    be.write_objects(objs)
    dead = list(range(lost))
    # -- device-resident slope (before the stores are mutated) -------------
    sl = be._shard_len(size)
    survivors = [s for s in range(K + M) if s not in dead]
    helper = sorted(be.coder.minimum_to_decode(dead, survivors))
    dev = _recovery_device_slope(be, objs, dead, helper, sl, fused_b)
    # -- end-to-end host path ----------------------------------------------
    # COLD first call includes the fused program's jit compile (~6s on
    # CPU, ~70s over the tunnel); the reference's objects/s has no
    # compile in it (C++ compiled offline), so the steady-state WARM
    # rate is the comparable number. Recover twice: the first call
    # compiles + rebuilds, the second (different replacement OSDs, same
    # shapes) hits every jit cache.
    for s in dead:
        cluster.stores.pop(be.acting[s], None)
    t0 = time.perf_counter()
    counters = be.recover_shards(dead,
                                 replacement_osds={s: 1000 + s
                                                   for s in dead},
                                 batch=fused_b)
    cold_dt = time.perf_counter() - t0
    for s in dead:
        cluster.stores.pop(be.acting[s], None)
    t0 = time.perf_counter()
    counters = be.recover_shards(dead,
                                 replacement_osds={s: 2000 + s
                                                   for s in dead},
                                 batch=fused_b)
    dt = time.perf_counter() - t0
    e2e_rate = objects / dt
    e2e_gbps = counters["bytes"] / dt / 1e9
    log(f"recovery e2e: {counters['bytes'] >> 20} MiB rebuilt over "
        f"{objects} x {size >> 20} MiB objects ({lost} shards lost, "
        f"fused batch {fused_b}) warm {dt:.2f}s = {e2e_rate:.1f} "
        f"objects/s, {e2e_gbps:.2f} GB/s (cold incl. compile: "
        f"{cold_dt:.2f}s = {objects / cold_dt:.1f} obj/s)")
    STATE["extra"]["recovery_objects_per_s"] = round(dev["objects_per_s"], 1)
    STATE["extra"]["recovery_rebuilt_gbps"] = dev["rebuilt_gbps"]
    STATE["extra"]["recovery_e2e"] = {
        "objects_per_s": round(e2e_rate, 1),
        "rebuilt_gbps": round(e2e_gbps, 3),
        "cold_objects_per_s": round(objects / cold_dt, 1),
        "fused_batch": fused_b,
        "timing": "warm steady state (staging pipeline, no compile); "
                  "cold includes jit compile"}
    return dev["objects_per_s"]


def _recovery_device_slope(be, objs, dead, helper, sl, fused_b):
    """Slope-time the fused recovery program (decode + both CRC
    passes) on device-resident helper stacks, digest-synced — and
    bit-verify it against the first batch's real shards first."""
    import functools as _ft

    import jax
    import jax.numpy as jnp
    import numpy as np
    from ceph_tpu.csum.kernels import crc32c_blocks
    from ceph_tpu.osd.ecbackend import shard_cid

    dec_fn = be.coder.batch_decoder(dead, helper)
    H, E = len(helper), len(dead)

    def fused(stack):                  # (B, H, sl) u8
        B_ = stack.shape[0]
        rebuilt = dec_fn(stack)        # (B, E, sl)
        rcrc = crc32c_blocks(rebuilt.reshape(B_ * E, sl),
                             init=0xFFFFFFFF, xorout=0)
        hcrc = crc32c_blocks(stack.reshape(B_ * H, sl),
                             init=0xFFFFFFFF, xorout=0)
        return rebuilt, rcrc, hcrc

    # correctness gate: one real batch, bit-compared to the original
    names = sorted(objs)[:fused_b]
    stack = np.stack([np.stack([be._store(s).read(
        shard_cid(be.pg, s), n) for s in helper]) for n in names])
    rebuilt = np.asarray(jax.jit(fused)(stack)[0])
    # shards for `dead` still exist at this point — compare directly
    for bi, n in enumerate(names):
        for ei, s in enumerate(dead):
            want = be._store(s).read(shard_cid(be.pg, s), n)
            if not (rebuilt[bi, ei] == want).all():
                raise AssertionError("fused recovery != stored shard")

    pool_n = 2
    key = jax.random.PRNGKey(7)
    pool = jax.random.randint(key, (pool_n, fused_b, H, sl), 0, 256,
                              dtype=jnp.uint8)

    @_ft.partial(jax.jit, static_argnums=1)
    def pipe(pool_arr, n):
        def body(acc, i):
            x = jax.lax.dynamic_index_in_dim(pool_arr, i % pool_n,
                                             keepdims=False)
            rebuilt_, rcrc, hcrc = fused(x)
            d = (jnp.bitwise_xor.reduce(rebuilt_, axis=None)
                 .astype(jnp.uint32)
                 ^ jnp.bitwise_xor.reduce(rcrc, axis=None)
                 ^ jnp.bitwise_xor.reduce(hcrc, axis=None))
            return acc ^ d, None
        acc, _ = jax.lax.scan(body, jnp.uint32(0),
                              jnp.arange(n, dtype=jnp.int32))
        return acc

    run = lambda n: int(jax.device_get(pipe(pool, n)))
    n2 = 32 if STATE["tpu_ok"] else 6
    gbps, t1, t2 = _slope(run, fused_b * len(dead) * sl,
                          n1=max(2, n2 // 8), n2=n2, reps=2)
    objects_per_s = gbps * 1e9 / (len(dead) * sl)
    log(f"recovery device slope: fused batch {fused_b} x {len(helper)} "
        f"helpers, {gbps:.2f} GB/s rebuilt = {objects_per_s:.1f} "
        f"objects/s (t1={t1:.2f}s t2={t2:.2f}s)")
    return {"objects_per_s": objects_per_s,
            "rebuilt_gbps": round(gbps, 3),
            "timing": "device-resident scan slope, digest-synced"}


def bench_lrc_repair(k=8, m=4, l=4):
    """LRC single-chunk repair (BASELINE row 3): k=8 m=4 l=4 — one lost
    data chunk repairs from its LOCAL group (l chunks read), not k.
    Reports repair GB/s (rebuilt bytes/s) and the measured
    helper-bytes/chunk-bytes ratio, vs k for plain RS (ref:
    src/erasure-code/lrc/ErasureCodeLrc.cc minimum_to_decode)."""
    import numpy as np
    from ceph_tpu.ec.registry import factory

    coder = factory(f"plugin=lrc k={k} m={m} l={l}")
    n = coder.get_chunk_count()
    chunk = 256 * 1024
    B = max(1, (64 << 20) // (k * chunk))
    rng = np.random.default_rng(21)
    data = rng.integers(0, 256, (B, k, chunk), np.uint8)
    parity = coder.encode_chunks(data)        # (B, n-k, chunk)
    # assemble the full stripe in POSITION order (LRC interleaves data
    # and local/global parity positions via its mapping string)
    data_pos = list(coder.data_positions)
    coding_pos = [i for i in range(n) if i not in set(data_pos)]
    full = np.zeros((B, n, chunk), np.uint8)
    full[:, data_pos] = data
    full[:, coding_pos] = parity
    lost = data_pos[0]                        # a data chunk
    avail = [i for i in range(n) if i != lost]
    helpers = sorted(coder.minimum_to_decode([lost], avail))
    ratio = len(helpers)                      # helper-bytes / chunk-bytes
    have = {h: full[:, h] for h in helpers}
    # correctness gate then timed repair
    rec = coder.decode_chunks([lost], have)
    if not (rec[lost] == full[:, lost]).all():
        raise AssertionError("lrc repair != original")
    # end-to-end host loop (numpy staging + tunnel transfer included) —
    # kept as the honesty lower bound
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        coder.decode_chunks([lost], have)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    e2e_gbps = B * chunk / best / 1e9
    # device-resident slope through the SAME fused path ECBackend
    # recovery launches (coder.batch_decoder — r5: the layered plan
    # collapses to one static GF matrix via ec/linearize), benched
    # exactly like encode (device-generated pool, scan pipeline,
    # digest sync) so the number measures the kernel, not the tunnel
    fn = coder.batch_decoder([lost], helpers)
    if fn is None:
        raise AssertionError("lrc batch_decoder unavailable for "
                             f"lost={lost} helpers={helpers}")
    got = np.asarray(fn(full[:, helpers]))[:, 0]
    if not (got == full[:, lost]).all():
        raise AssertionError("lrc device repair fn != original")
    pool = _device_pool((SUB, len(helpers), chunk), 31)
    run = _pipeline(fn, pool)
    gbps, t1, t2 = _slope(run, SUB * chunk)   # rebuilt bytes/iter
    res = {"repair_gbps": round(gbps, 3), "helper_chunks": ratio,
           "rs_helper_chunks": k, "io_savings": round(k / ratio, 2),
           "e2e_host_gbps": round(e2e_gbps, 3),
           "timing": "device-resident slope; e2e_host includes staging"}
    STATE["extra"]["lrc_repair_k8m4l4"] = res
    log(f"lrc k={k} m={m} l={l} repair: {gbps:.2f} GB/s rebuilt "
        f"(kernel slope; e2e host {e2e_gbps:.3f}), {ratio} helper "
        f"chunks vs {k} for RS (I/O savings {k / ratio:.1f}x)")
    return res


def bench_clay_repair(k=8, m=4, d=11):
    """Clay MSR single-chunk repair (BASELINE row 4): k=8 m=4 d=11 —
    each of d helpers contributes only beta = 1/(d-k+1) of its bytes.
    Reports repair GB/s and the measured helper-bytes/(k*chunk) ratio
    vs 1.0 for plain RS (ref: src/erasure-code/clay/ErasureCodeClay.cc
    minimum_to_decode sub-chunk ranges)."""
    import numpy as np
    from ceph_tpu.ec.registry import factory

    coder = factory(f"plugin=clay k={k} m={m} d={d}")
    sub_count = coder.get_sub_chunk_count()
    chunk = 256 * 1024
    assert chunk % sub_count == 0
    B = max(1, (32 << 20) // (k * chunk))
    rng = np.random.default_rng(22)
    data = rng.integers(0, 256, (B, k, chunk), np.uint8)
    parity = coder.encode_chunks(data)        # (B, m, chunk)
    full = np.concatenate([data, parity], axis=1)   # (B, k+m, chunk)
    lost = 0
    avail = [i for i in range(k + m) if i != lost]
    need = coder.minimum_to_decode_subchunks(lost, avail)
    helper_bytes = sum(len(planes) for planes in need.values()) \
        * (chunk // sub_count)
    beta_ratio = helper_bytes / (k * chunk)   # vs full-rebuild read
    have = {h: full[:, h] for h in need}
    rec = coder.repair_from_chunks(lost, have)
    if not (rec == full[:, lost]).all():
        raise AssertionError("clay repair != original")
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        coder.repair_from_chunks(lost, have)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    e2e_gbps = B * chunk / best / 1e9
    # device-resident slope through the SAME fused path ECBackend
    # recovery launches (coder.batch_decoder: full helper stack in,
    # repair-plane selection ON DEVICE, one matrix-apply out)
    helpers = sorted(need)
    fn = coder.batch_decoder([lost], helpers)
    if fn is None:
        raise AssertionError("clay batch_decoder unavailable for "
                             f"lost={lost} helpers={helpers}")
    got = np.asarray(fn(full[:, helpers]))[:, 0]
    if not (got == full[:, lost]).all():
        raise AssertionError("clay device repair fn != original")
    pool = _device_pool((SUB, len(helpers), chunk), 32)
    run = _pipeline(fn, pool)
    gbps, t1, t2 = _slope(run, SUB * chunk)   # rebuilt bytes/iter
    res = {"repair_gbps": round(gbps, 3),
           "helper_bytes_ratio_vs_rs": round(beta_ratio, 4),
           "theory_ratio": round(d / ((d - k + 1) * k), 4),
           "io_savings": round(1.0 / beta_ratio, 2),
           "e2e_host_gbps": round(e2e_gbps, 3),
           "timing": "device-resident slope; e2e_host includes staging"}
    STATE["extra"]["clay_repair_k8m4d11"] = res
    log(f"clay k={k} m={m} d={d} repair: {gbps:.2f} GB/s rebuilt "
        f"(kernel slope; e2e host {e2e_gbps:.3f}), helper bytes = "
        f"{beta_ratio:.3f} of RS full-read "
        f"(theory {d / ((d - k + 1) * k):.3f}, savings "
        f"{1.0 / beta_ratio:.1f}x)")
    return res


def bench_wire(seconds=None):
    """Wire-tier throughput (VERDICT r4 item 8; ref: src/tools/rados/
    rados.cc `rados bench`): tools/rados_bench.py against a standalone
    cluster — N real-socket daemons, cephx auth, AES-GCM secure
    frames. Runs in a CPU-pinned subprocess: it measures the messenger
    stack on localhost, not the chip, and must not touch the tunnel."""
    if seconds is None:   # parse inside the section's isolation, not
        seconds = float(  # at import (a malformed env var must fail
            os.environ.get("BENCH_WIRE_SECONDS", "4"))  # ONE section)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "rados_bench.py")
    out = {}
    for workload in ("write", "seq"):
        r = subprocess.run(
            [sys.executable, tool, "--transport", "standalone",
             "--seconds", str(seconds), "--object-size", "65536",
             "--num-osds", "6", "--pg-num", "4", "--batch", "8",
             "--window", "8", "--json", workload],
            capture_output=True, text=True, timeout=240, env=env)
        if r.returncode != 0:
            tail = " | ".join((r.stderr or "").strip()
                              .splitlines()[-3:])[:200]
            raise RuntimeError(
                f"rados_bench {workload} rc={r.returncode}: {tail}")
        line = (r.stdout.strip().splitlines() or ["{}"])[-1]
        d = json.loads(line)
        if d.get("mb_per_s") is None:
            raise RuntimeError(
                f"rados_bench {workload} emitted no metrics: "
                f"{line[:200]}")
        out[workload] = {k: d.get(k) for k in
                         ("mb_per_s", "ops_per_s", "objects_per_s",
                          "p50_ms", "p95_ms")}
        log(f"wire {workload}: {d.get('mb_per_s')} MB/s "
            f"{d.get('objects_per_s')} obj/s p50={d.get('p50_ms')}ms")
    out["config"] = {"transport": "standalone", "cephx": True,
                     "secure": True, "object_size": 65536, "batch": 8,
                     "window": 8, "pg_num": 4,
                     "n_osds": 6, "backend": "cpu (messenger bench)"}
    STATE["extra"]["wire_rados_bench"] = out
    return out


_TRANSIENT = ("remote_compile", "HTTP 500", "DEADLINE_EXCEEDED")

# keys that prove a child section actually measured something
_SECTION_DONE_KEYS = {
    "recovery": ("recovery_objects_per_s",),
    "crush": ("crush_placements_per_s",),
    "lrc": ("lrc_repair_k8m4l4",),
    "clay": ("clay_repair_k8m4d11",),
}

# per-attempt env overrides: attempt 1 shrinks the working set (the
# known crash modes are compile/working-set pressure, not flakes)
_SECTION_LADDER = {
    "recovery": ({}, {"BENCH_RECOVERY_BATCH": "2"}),
    "crush": ({}, {"BENCH_CRUSH_SUB": "5000"}),
}


def _section_isolated(name: str, skip: set, fn, *, timeout: float,
                      **kw):
    """Run a crash-prone section in a subprocess with its own JAX
    client (TPU path only — the CPU fallback cannot crash a worker and
    subprocessing it would just pay jit cache misses twice). A child
    that dies, hangs, or comes back CPU-only is retried once with a
    smaller working set; its measured extras merge into STATE."""
    force = os.environ.get("BENCH_FORCE_ISOLATE") == "1"
    if not STATE["tpu_ok"] and not force:
        return _section(name, skip, fn, **kw)
    if name in skip:
        log(f"section {name}: skipped via BENCH_SKIP")
        return None
    ladder = _SECTION_LADDER.get(name, ({},))
    merged_prev: list = []
    for attempt, overrides in enumerate(ladder):
        budget = DEADLINE - (time.monotonic() - T0) - 45.0
        if budget < 90.0:
            fail(f"section {name}", "no deadline budget left for child")
            return None
        child_timeout = min(timeout, budget)
        env = dict(os.environ)
        env.update(overrides)
        env["BENCH_SECTION_ONLY"] = name
        env["BENCH_TPU_WAIT"] = "120"
        env["BENCH_DEADLINE"] = str(int(child_timeout - 15.0))
        if not STATE["tpu_ok"]:
            # forced-isolation exercise on a CPU host: pin the child
            # to CPU outright instead of letting it probe the dead
            # tunnel for 120s per attempt
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
        log(f"section {name}: child attempt {attempt} "
            f"(timeout {child_timeout:.0f}s, overrides {overrides})")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=child_timeout,
                env=env)
            sys.stderr.write(r.stderr)
            payload = None
            for line in reversed(r.stdout.strip().splitlines() or []):
                try:
                    cand = json.loads(line)
                except ValueError:
                    continue
                if isinstance(cand, dict):   # a noisy child can print
                    payload = cand           # bare JSON scalars too
                    break
            if payload is None:
                raise RuntimeError(f"child rc={r.returncode}, no JSON")
        except subprocess.TimeoutExpired:
            fail(f"section {name}",
                 f"child hung > {child_timeout:.0f}s (worker wedge?)")
            time.sleep(45.0)   # let a crashed worker restart
            continue
        except Exception as e:   # noqa: BLE001 — isolate the child
            fail(f"section {name}", e)
            time.sleep(30.0)
            continue
        for err in payload.get("errors", []):
            STATE["errors"].append(f"[child {name}] {err}"[:300])
        # a CPU child is acceptable ONLY when the parent itself is on
        # CPU (the forced-isolation test path) — a TPU artifact must
        # never absorb a fallback child's shrunk CPU numbers, forced
        # or not
        if not payload.get("tpu_ok") and STATE["tpu_ok"]:
            fail(f"section {name}",
                 f"child fell back to {payload.get('backend')}; "
                 f"not merging CPU numbers into a TPU artifact")
            time.sleep(30.0)
            continue
        # a retry attempt ran under DIFFERENT overrides: drop the
        # previous attempt's partial keys so one artifact never mixes
        # measurements from two configs
        for k in merged_prev:
            STATE["extra"].pop(k, None)
        merged = []
        for k, v in payload.get("extra", {}).items():
            if k not in STATE["extra"]:
                STATE["extra"][k] = v
                merged.append(k)
        merged_prev = merged
        done = all(k in STATE["extra"]
                   for k in _SECTION_DONE_KEYS.get(name, ()))
        log(f"section {name}: child merged {merged} done={done}")
        if done:
            return True
    return None


def _child_main(name: str) -> None:
    """BENCH_SECTION_ONLY mode: acquire a backend, run ONE section,
    print the child JSON line (see emit)."""
    _watchdog()
    global SUB, N2
    try:
        plat = acquire_backend()
        STATE["backend"] = plat
        STATE["tpu_ok"] = plat not in (None, "cpu")
        if plat == "cpu":
            _force_cpu()
            SUB = min(SUB, 4)
            N2 = min(N2, 10)
        import jax
        log(f"child[{name}] backend={jax.default_backend()}")
        # the child is a FRESH process: point it at the same persistent
        # compile cache so an isolated cold section (recovery, crush)
        # loads executables the parent — or a previous run — compiled
        from ceph_tpu.utils.jax_cache import \
            enable_persistent_compile_cache
        enable_persistent_compile_cache()
        try:
            from ceph_tpu import native as _native
            _native.build()
        except Exception:   # noqa: BLE001 — no compiler on host
            pass
        fns = {"encode": lambda: bench_encode_impls(["mxu", "bitlinear"]),
               "decode": lambda: bench_decode(["mxu", "bitlinear"]),
               "cpu": bench_cpu_native,
               "lrc": bench_lrc_repair,
               "clay": bench_clay_repair,
               "recovery": bench_recovery,
               "crush": bench_crush}
        _section(name, set(), fns[name])
    except BaseException as e:   # noqa: BLE001 — the line must print
        fail(f"child {name}", e)
    emit()
    sys.exit(0)


def _section(name: str, skip: set, fn, *a, **kw):
    if name in skip:
        log(f"section {name}: skipped via BENCH_SKIP")
        return None
    log(f"section {name}: start")
    for attempt in (0, 1):
        try:
            return fn(*a, **kw)
        except Exception as e:    # noqa: BLE001 — section isolation
            # one retry on known-transient axon-side failures (the
            # 2026-07-31 capture lost recovery to a one-off
            # compile-helper HTTP 500); everything else fails the
            # section immediately
            msg = f"{e!r}"
            if attempt == 0 and any(t in msg for t in _TRANSIENT):
                log(f"section {name}: transient failure "
                    f"({msg[:120]}); retrying in 30s")
                time.sleep(30.0)
                continue
            fail(f"section {name}", e)
            return None


def main() -> None:
    if CHILD_SECTION:
        _child_main(CHILD_SECTION)
        return
    _watchdog()
    global SUB, N2
    try:
        plat = acquire_backend()
        STATE["backend"] = plat
        STATE["tpu_ok"] = plat not in (None, "cpu")
        if plat == "cpu":
            _force_cpu()
            # interpreter-speed backend: shrink the working set so the
            # CPU fallback still finishes inside the deadline
            SUB = min(SUB, 4)
            N2 = min(N2, 10)
        import jax
        log(f"backend={jax.default_backend()} devices={jax.devices()}")

        # persistent jit cache scoped under the bench workdir: cold
        # sections (and cold CHILD sections — recovery/crush run in
        # fresh subprocesses) load serialized executables instead of
        # re-paying every compile; the native codec builds once here
        from ceph_tpu.utils.jax_cache import \
            enable_persistent_compile_cache
        cache = enable_persistent_compile_cache()
        if cache:
            STATE["extra"]["jax_compile_cache"] = cache
        try:
            from ceph_tpu import native as _native
            _native.build()
        except Exception as e:   # noqa: BLE001 — no compiler on host
            log(f"native build skipped: {e}")

        # pallas is retired to experiment status (r4 on-chip: 11.2 vs
        # 85.0 GB/s for plain-XLA mxu — docs/BENCH_METHODOLOGY.md
        # "Kernel findings"); opt back in via BENCH_IMPLS=...,pallas
        default_impls = "mxu,bitlinear"
        impls = [i for i in os.environ.get(
            "BENCH_IMPLS", default_impls).split(",") if i]

        skip = set(os.environ.get("BENCH_SKIP", "").split(","))
        _section("encode", skip, bench_encode_impls, impls)
        _section("decode", skip, bench_decode, impls)
        _section("cpu", skip, bench_cpu_native)
        _section("wire", skip, bench_wire)
        _section("lrc", skip, bench_lrc_repair)
        _section("clay", skip, bench_clay_repair)
        # recovery + crush are the two sections that have crashed the
        # remote compile helper / TPU worker in live captures; they run
        # LAST and in SUBPROCESSES (fresh JAX client each) so a crash
        # costs one child, not the window (r4: the 03:17Z crush crash
        # wedged the tunnel and forfeited both numbers)
        _section_isolated("recovery", skip, bench_recovery, timeout=600.0)
        _section_isolated("crush", skip, bench_crush, timeout=450.0)
    except BaseException as e:    # noqa: BLE001 — the line must print
        fail("main", e)
    emit()
    sys.exit(0)


if __name__ == "__main__":
    main()
