"""librados-shaped client API + radosstriper analog.

Rebuild of the reference's public object API (ref: src/librados/
librados.cc `rados_write/rados_write_full/rados_read/rados_remove/
rados_stat`, RadosClient/IoCtxImpl split; python binding shape ref:
src/pybind/rados/rados.pyx — Rados.open_ioctx -> IoCtx methods) and of
the client-side striper (ref: src/libradosstriper/
RadosStriperImpl.cc — a logical byte stream striped round-robin in
stripe_unit pieces across stripe_count rados objects of object_size
each; the layout ref: libradosstriper's default one-object-set
striping, same math as ECUtil's round-robin but client-side).

Everything routes through the Objecter (retry/retarget on map change),
so callers get the same semantics librados users get: write during a
remap lands correctly without caller involvement.
"""

from __future__ import annotations

import numpy as np

from .objecter import Objecter


class Rados:
    """Cluster handle (the RadosClient role)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._objecter = Objecter(cluster)

    def open_ioctx(self, pool: str = "default") -> "IoCtx":
        # the sim carries one pool (id 1); named lookup mirrors
        # rados_ioctx_create's pool-name resolution
        if pool not in ("default", "1"):
            raise ValueError(f"no pool {pool!r}")
        return IoCtx(self, pool)

    def stat_cluster(self) -> dict:
        return self.cluster.health()


def sim_clock(ioctx: "IoCtx") -> float:
    """The sim cluster's VIRTUAL clock when present — 0.0 included
    (an `or time.time()` would silently mix wall-clock into virtual
    time and break age math); wall time only without a sim cluster.
    Shared by every service layer (RGW mtimes, FS mtimes)."""
    import time
    now = getattr(ioctx.rados.cluster, "now", None)
    return time.time() if now is None else now


class IoCtx:
    """Per-pool I/O context (IoCtxImpl)."""

    def __init__(self, rados: Rados, pool: str):
        self.rados = rados
        self.pool = pool
        self._ob = rados._objecter

    # -- object ops (librados C API names) ----------------------------------

    def write_full(self, name: str, data: bytes | np.ndarray,
                   snapc: int = 0) -> None:
        self._ob.write({name: data}, snapc=snapc)

    def write(self, name: str, data: bytes | np.ndarray,
              offset: int = 0, snapc: int = 0) -> None:
        self._ob.write_at(name, offset, data, snapc=snapc)

    def read(self, name: str, length: int | None = None,
             offset: int = 0, snap: int | None = None) -> bytes:
        """`snap` reads the object's state as of that pool snapshot
        (the rados_ioctx_snap_set_read role, per-call instead of
        sticky context)."""
        if snap is None:
            arr = self._ob.read(name)
        else:
            arr = self.rados.cluster.snap_read(name, snap)
        if length is None:
            return arr[offset:].tobytes()
        return arr[offset:offset + length].tobytes()

    def remove(self, name: str, snapc: int = 0) -> None:
        self._ob.remove(name, snapc=snapc)

    def stat(self, name: str) -> int:
        """Object size in bytes (rados_stat's pmtime is meaningless in
        virtual time)."""
        ps = self.rados.cluster.locate(name)
        return self.rados.cluster.pgs[ps].stat_object(name)

    def list_objects(self) -> list[str]:
        c = self.rados.cluster
        return sorted(n for ps in range(c.pg_num)
                      for n in c.pgs[ps].list_pg_objects())

    # -- pool snapshots (rados_ioctx_snap_*) --------------------------------

    def snap_create(self) -> int:
        return self.rados.cluster.snap_create()

    def snap_remove(self, snap_id: int) -> int:
        return self.rados.cluster.snap_remove(snap_id)

    def snap_rollback(self, name: str, snap_id: int) -> None:
        self.rados.cluster.snap_rollback(name, snap_id)

    def snap_list(self) -> list[int]:
        return sorted(self.rados.cluster.snaps)

    # -- selfmanaged snaps (rados_ioctx_selfmanaged_snap_*) -----------------

    def selfmanaged_snap_create(self) -> int:
        return self.rados.cluster.selfmanaged_snap_create()

    def selfmanaged_snap_remove(self, snap_id: int) -> int:
        return self.rados.cluster.selfmanaged_snap_remove(snap_id)

    def snap_changed(self, name: str, snap_id: int) -> bool:
        """Fast-diff primitive: head diverged from its state at the
        snap? (metadata-only; ref: librbd fast-diff / object map)"""
        return self.rados.cluster.snap_changed(name, snap_id)

    # -- watch / notify (rados_watch3/rados_notify2) ------------------------

    def watch(self, name: str, callback) -> int:
        return self.rados.cluster.watch(name, callback)

    def unwatch(self, name: str, cookie: int) -> None:
        self.rados.cluster.unwatch(name, cookie)

    def notify(self, name: str, payload: bytes = b"") -> dict:
        return self.rados.cluster.notify(name, payload)

    # -- object classes (rados_exec) ----------------------------------------

    def execute(self, name: str, cls: str, method: str,
                inp: bytes = b"") -> bytes:
        return self.rados.cluster.cls_exec(name, cls, method, inp)


class RadosStriper:
    """Client-side striping over rados objects (libradosstriper).

    A logical byte stream `soid` maps to objects `{soid}.{q:016x}`:
    logical offset L lives in stripe-unit su = (L // stripe_unit),
    which round-robins onto object (su % stripe_count) within an
    object set of stripe_count objects; object sets advance every
    stripe_count * object_size logical bytes. Size is tracked in a
    striper metadata object (the striper's size xattr role).
    """

    def __init__(self, ioctx: IoCtx, stripe_unit: int = 1 << 16,
                 stripe_count: int = 4, object_size: int = 1 << 22):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        if stripe_count < 1 or stripe_unit < 1:
            raise ValueError("bad striping parameters")
        self.io = ioctx
        self.su = stripe_unit
        self.sc = stripe_count
        self.osz = object_size

    def _obj(self, soid: str, q: int) -> str:
        return f"{soid}.{q:016x}"

    def _meta(self, soid: str) -> str:
        return f"{soid}.meta"

    def _extents(self, offset: int, length: int):
        """Yield (object index, object offset, logical offset, len)
        pieces covering [offset, offset+length)."""
        units_per_set = self.sc * (self.osz // self.su)
        pos = offset
        end = offset + length
        while pos < end:
            su_idx = pos // self.su
            intra = pos % self.su
            take = min(self.su - intra, end - pos)
            obj_set, in_set = divmod(su_idx, units_per_set)
            obj_in_set = in_set % self.sc
            row = in_set // self.sc          # stripe row within the set
            q = obj_set * self.sc + obj_in_set
            ooff = row * self.su + intra
            yield q, ooff, pos, take
            pos += take

    def piece_extents(self, q: int, upto: int):
        """Logical (offset, len) extents mapping to piece object q,
        clamped to [0, upto) — the inverse of the _extents walk. Lives
        here so ONE class owns the striping geometry (RBD clone
        copy-up and diff depend on it)."""
        rows = self.osz // self.su
        units_per_set = self.sc * rows
        obj_set, obj_in_set = divmod(q, self.sc)
        for row in range(rows):
            unit = obj_set * units_per_set + row * self.sc + obj_in_set
            loff = unit * self.su
            if loff >= upto:
                break
            yield loff, min(self.su, upto - loff)

    def _read_meta(self, soid: str,
                   snap: int | None = None) -> tuple[int, int]:
        """(logical size, high-water-mark size). The hwm tracks the
        LARGEST size the stream ever had, so remove() can find pieces
        a later truncate-shrink left behind (zeroed but extant). Old
        8-byte metas (pre-hwm) read back hwm == size."""
        try:
            raw = bytes(self.io.read(self._meta(soid), snap=snap))
        except KeyError:
            raise KeyError(f"no striped object {soid!r}")
        size = int.from_bytes(raw[:8], "little")
        hwm = int.from_bytes(raw[8:16], "little") if len(raw) >= 16 \
            else size
        return size, max(size, hwm)

    def _write_meta(self, soid: str, size: int, hwm: int,
                    snapc: int = 0) -> None:
        self.io.write_full(self._meta(soid),
                           size.to_bytes(8, "little")
                           + hwm.to_bytes(8, "little"), snapc=snapc)

    def size(self, soid: str, snap: int | None = None) -> int:
        return self._read_meta(soid, snap=snap)[0]

    def write(self, soid: str, data: bytes | np.ndarray,
              offset: int = 0, snapc: int = 0) -> None:
        arr = np.frombuffer(bytes(data), dtype=np.uint8) \
            if isinstance(data, (bytes, bytearray, memoryview)) \
            else np.asarray(data, np.uint8).reshape(-1)
        for q, ooff, lpos, ln in self._extents(offset, len(arr)):
            piece = arr[lpos - offset:lpos - offset + ln]
            self.io.write(self._obj(soid, q), piece, offset=ooff,
                          snapc=snapc)
        try:
            cur, hwm = self._read_meta(soid)
        except KeyError:
            cur = hwm = 0
        new = max(cur, offset + len(arr))
        if new != cur:
            self._write_meta(soid, new, max(hwm, new), snapc=snapc)

    def read(self, soid: str, length: int | None = None,
             offset: int = 0, snap: int | None = None) -> bytes:
        total = self.size(soid, snap=snap)
        if length is None:
            length = max(0, total - offset)
        length = min(length, max(0, total - offset))
        out = np.zeros(length, dtype=np.uint8)
        if not length:
            return b""
        cache: dict[str, np.ndarray] = {}
        for q, ooff, lpos, ln in self._extents(offset, length):
            name = self._obj(soid, q)
            if name not in cache:
                try:
                    cache[name] = np.frombuffer(
                        self.io.read(name, snap=snap), dtype=np.uint8)
                except KeyError:
                    cache[name] = np.zeros(0, dtype=np.uint8)
            obj = cache[name]
            piece = obj[ooff:ooff + ln]
            out[lpos - offset:lpos - offset + len(piece)] = piece
        return out.tobytes()

    def truncate(self, soid: str, new_size: int,
                 zero_chunk: int = 1 << 20, snapc: int = 0) -> None:
        """Shrink (or grow) the logical stream. A shrink ZEROES the
        discarded range before dropping the size, so a later re-grow
        reads zeros there, not resurrected bytes (the block-device
        contract; the reference trims/zeroes objects)."""
        if new_size < 0:
            raise ValueError(f"truncate to {new_size} < 0")
        old, hwm = self._read_meta(soid)
        if new_size < old:
            pos = new_size
            while pos < old:
                n = min(zero_chunk, old - pos)
                self.write(soid, b"\x00" * n, offset=pos, snapc=snapc)
                pos += n
        self._write_meta(soid, new_size, max(hwm, new_size),
                         snapc=snapc)

    def remove(self, soid: str, snapc: int = 0) -> None:
        # walk to the HIGH-WATER mark, not the current size: a
        # truncate-shrink keeps (zeroed) pieces past the new boundary
        # that a size-bounded walk would leak forever
        _, hwm = self._read_meta(soid)
        qs = {q for q, _, _, _ in self._extents(0, max(hwm, 1))}
        for q in sorted(qs):
            try:
                self.io.remove(self._obj(soid, q), snapc=snapc)
            except KeyError:
                pass  # sparse stripe: unit never written
        self.io.remove(self._meta(soid), snapc=snapc)
