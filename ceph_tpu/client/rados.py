"""librados-shaped client API + radosstriper analog.

Rebuild of the reference's public object API (ref: src/librados/
librados.cc `rados_write/rados_write_full/rados_read/rados_remove/
rados_stat`, RadosClient/IoCtxImpl split; python binding shape ref:
src/pybind/rados/rados.pyx — Rados.open_ioctx -> IoCtx methods) and of
the client-side striper (ref: src/libradosstriper/
RadosStriperImpl.cc — a logical byte stream striped round-robin in
stripe_unit pieces across stripe_count rados objects of object_size
each; the layout ref: libradosstriper's default one-object-set
striping, same math as ECUtil's round-robin but client-side).

Everything routes through the Objecter (retry/retarget on map change),
so callers get the same semantics librados users get: write during a
remap lands correctly without caller involvement.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .objecter import Objecter


class Completion:
    """An in-flight async op (the rados_completion_t role, ref:
    src/librados/AioCompletionImpl.h): wait_for_complete blocks,
    is_complete polls, get_return_value yields the op's result (and
    re-raises its failure — librados returns the negative errno the
    same way)."""

    def __init__(self, callback=None):
        self._ev = threading.Event()
        self._cb = callback
        self._result = None
        self._exc: BaseException | None = None
        self._done = False

    def _finish(self, result, exc) -> None:
        self._result, self._exc = result, exc
        self._done = True       # value readable (e.g. FROM the cb)
        if self._cb is not None:
            try:
                self._cb(self)
            except Exception:   # noqa: BLE001 — a broken user callback
                pass            # must not kill the completion thread
        # signaled only AFTER the callback ran — librados order: a
        # wait_for_complete/aio_flush returning guarantees callbacks
        # finished too (aggregates built in callbacks are whole)
        self._ev.set()

    def is_complete(self) -> bool:
        return self._ev.is_set()

    def wait_for_complete(self, timeout: float | None = None) -> bool:
        return self._ev.wait(timeout)

    def get_return_value(self):
        if not self._done:
            self._ev.wait()
        if self._exc is not None:
            raise self._exc
        return self._result


class Rados:
    """Cluster handle (the RadosClient role)."""

    def __init__(self, cluster, aio_threads: int = 4):
        self.cluster = cluster
        self._objecter = Objecter(cluster)
        # the finisher/op thread pool behind aio_* (ref: librados'
        # Objecter op threads + the AioCompletion finisher): ops run
        # here, completions fire from here; created LAZILY so sync-only
        # handles never spawn threads. The Objecter serializes
        # dispatch under its own (reentrant) lock, so concurrency is
        # safe; aio buys PIPELINING of staging/callback work.
        self._aio_threads = aio_threads
        self._aio: ThreadPoolExecutor | None = None
        self._aio_lock = threading.Lock()
        self._aio_inflight: set = set()

    def shutdown(self) -> None:
        """rados_shutdown: drain in-flight aio and join the worker
        threads. The handle stays usable for SYNC ops afterwards; a
        later aio op lazily rebuilds the pool."""
        with self._aio_lock:
            pool, self._aio = self._aio, None
        if pool is not None:
            pool.shutdown(wait=True)

    def open_ioctx(self, pool: str = "default") -> "IoCtx":
        # the sim carries one pool (id 1); named lookup mirrors
        # rados_ioctx_create's pool-name resolution
        if pool not in ("default", "1"):
            raise ValueError(f"no pool {pool!r}")
        return IoCtx(self, pool)

    def stat_cluster(self) -> dict:
        return self.cluster.health()


def sim_clock(ioctx: "IoCtx") -> float:
    """The sim cluster's VIRTUAL clock when present — 0.0 included
    (an `or time.time()` would silently mix wall-clock into virtual
    time and break age math); wall time only without a sim cluster.
    Shared by every service layer (RGW mtimes, FS mtimes)."""
    import time
    now = getattr(ioctx.rados.cluster, "now", None)
    return time.time() if now is None else now


class IoCtx:
    """Per-pool I/O context (IoCtxImpl)."""

    def __init__(self, rados: Rados, pool: str):
        self.rados = rados
        self.pool = pool
        self._ob = rados._objecter

    # -- object ops (librados C API names) ----------------------------------

    def write_full(self, name: str, data: bytes | np.ndarray,
                   snapc: int = 0) -> None:
        self._ob.write({name: data}, snapc=snapc)

    def write(self, name: str, data: bytes | np.ndarray,
              offset: int = 0, snapc: int = 0) -> None:
        self._ob.write_at(name, offset, data, snapc=snapc)

    def append(self, name: str, data: bytes | np.ndarray,
               snapc: int = 0) -> int:
        """rados_append: bytes land at the object's current tail (the
        primary resolves the size server-side, so concurrent appenders
        serialize there). Returns the landed offset. On an EC pool a
        tail inside stripe padding takes the r16 no-preread fast
        path."""
        return self._ob.append(name, data, snapc=snapc)

    def read(self, name: str, length: int | None = None,
             offset: int = 0, snap: int | None = None) -> bytes:
        """`snap` reads the object's state as of that pool snapshot
        (the rados_ioctx_snap_set_read role, per-call instead of
        sticky context)."""
        if snap is None:
            arr = self._ob.read(name)
        else:
            with self._ob._dispatch_lock:
                arr = self.rados.cluster.snap_read(name, snap)
        if length is None:
            return arr[offset:].tobytes()
        return arr[offset:offset + length].tobytes()

    def read_many(self, names) -> dict[str, bytes]:
        """Batched reads: one submission per PG, each decoded in one
        batched launch (the aio_read-batch role; wire-tier Client
        .read_many parity). Rides the Objecter, so the degraded-read
        fast path covers these too — a dead primary costs a decode
        from surviving shards, not a detection wait."""
        got = self._ob.read(list(names))
        return {n: arr.tobytes() for n, arr in got.items()}

    def remove(self, name: str, snapc: int = 0) -> None:
        self._ob.remove(name, snapc=snapc)

    def stat(self, name: str) -> int:
        """Object size in bytes (rados_stat's pmtime is meaningless in
        virtual time). Serialized with in-flight aio — PG state is
        not thread-safe (see Objecter._dispatch_lock)."""
        with self._ob._dispatch_lock:
            ps = self.rados.cluster.locate(name)
            return self.rados.cluster.pgs[ps].stat_object(name)

    def list_objects(self) -> list[str]:
        with self._ob._dispatch_lock:
            c = self.rados.cluster
            return sorted(n for ps in range(c.pg_num)
                          for n in c.pgs[ps].list_pg_objects())

    # -- async ops (rados_aio_*, ref: librados.cc rados_aio_write/
    #    rados_aio_read/rados_aio_flush over AioCompletionImpl) -------------

    def _aio_submit(self, fn, callback) -> Completion:
        comp = Completion(callback)
        r = self.rados

        def run():
            try:
                comp._finish(fn(), None)
            except BaseException as e:   # noqa: BLE001 — surfaces via
                comp._finish(None, e)    # get_return_value, as errno
            finally:
                with r._aio_lock:
                    r._aio_inflight.discard(comp)
        # pool-get + inflight-add + submit under ONE lock window: a
        # concurrent shutdown() between them would otherwise leave a
        # registered-but-never-run completion that hangs aio_flush
        # forever (shutdown swaps the pool out under the same lock)
        with r._aio_lock:
            if r._aio is None:
                r._aio = ThreadPoolExecutor(
                    max_workers=r._aio_threads,
                    thread_name_prefix="rados-aio")
            r._aio_inflight.add(comp)
            try:
                r._aio.submit(run)
            except RuntimeError:
                r._aio_inflight.discard(comp)
                raise
        return comp

    def aio_write_full(self, name: str, data: bytes,
                       callback=None, snapc: int = 0) -> Completion:
        data = bytes(data)   # snapshot the buffer at submit time
        return self._aio_submit(
            lambda: self.write_full(name, data, snapc=snapc) or len(data),
            callback)

    def aio_write(self, name: str, data: bytes, offset: int = 0,
                  callback=None, snapc: int = 0) -> Completion:
        data = bytes(data)
        return self._aio_submit(
            lambda: self.write(name, data, offset=offset,
                               snapc=snapc) or len(data),
            callback)

    def aio_read(self, name: str, length: int | None = None,
                 offset: int = 0, callback=None) -> Completion:
        return self._aio_submit(
            lambda: self.read(name, length=length, offset=offset),
            callback)

    def aio_remove(self, name: str, callback=None,
                   snapc: int = 0) -> Completion:
        return self._aio_submit(
            lambda: self.remove(name, snapc=snapc), callback)

    def aio_flush(self, comps: list[Completion] | None = None) -> None:
        """Barrier: wait until outstanding aio completes (ref:
        rados_aio_flush). With a list, waits those; with None, every
        op in flight at the moment of the call (ops submitted AFTER
        the flush began are not covered, as upstream)."""
        if comps is None:
            with self.rados._aio_lock:
                comps = list(self.rados._aio_inflight)
        for c in comps:
            c.wait_for_complete()

    # -- pool snapshots (rados_ioctx_snap_*) --------------------------------

    def snap_create(self) -> int:
        with self._ob._dispatch_lock:
            return self.rados.cluster.snap_create()

    def snap_remove(self, snap_id: int) -> int:
        with self._ob._dispatch_lock:
            return self.rados.cluster.snap_remove(snap_id)

    def snap_rollback(self, name: str, snap_id: int) -> None:
        with self._ob._dispatch_lock:
            self.rados.cluster.snap_rollback(name, snap_id)

    def snap_list(self) -> list[int]:
        with self._ob._dispatch_lock:
            return sorted(self.rados.cluster.snaps)

    # -- selfmanaged snaps (rados_ioctx_selfmanaged_snap_*) -----------------

    def selfmanaged_snap_create(self) -> int:
        with self._ob._dispatch_lock:
            return self.rados.cluster.selfmanaged_snap_create()

    def selfmanaged_snap_remove(self, snap_id: int) -> int:
        with self._ob._dispatch_lock:
            return self.rados.cluster.selfmanaged_snap_remove(snap_id)

    def snap_changed(self, name: str, snap_id: int) -> bool:
        """Fast-diff primitive: head diverged from its state at the
        snap? (metadata-only; ref: librbd fast-diff / object map)"""
        with self._ob._dispatch_lock:
            return self.rados.cluster.snap_changed(name, snap_id)

    # -- watch / notify (rados_watch3/rados_notify2) ------------------------

    def watch(self, name: str, callback) -> int:
        with self._ob._dispatch_lock:
            return self.rados.cluster.watch(name, callback)

    def unwatch(self, name: str, cookie: int) -> None:
        with self._ob._dispatch_lock:
            self.rados.cluster.unwatch(name, cookie)

    def notify(self, name: str, payload: bytes = b"") -> dict:
        with self._ob._dispatch_lock:
            return self.rados.cluster.notify(name, payload)

    # -- object classes (rados_exec) ----------------------------------------

    def execute(self, name: str, cls: str, method: str,
                inp: bytes = b"") -> bytes:
        with self._ob._dispatch_lock:
            return self.rados.cluster.cls_exec(name, cls, method, inp)


class RadosStriper:
    """Client-side striping over rados objects (libradosstriper).

    A logical byte stream `soid` maps to objects `{soid}.{q:016x}`:
    logical offset L lives in stripe-unit su = (L // stripe_unit),
    which round-robins onto object (su % stripe_count) within an
    object set of stripe_count objects; object sets advance every
    stripe_count * object_size logical bytes. Size is tracked in a
    striper metadata object (the striper's size xattr role).
    """

    def __init__(self, ioctx: IoCtx, stripe_unit: int = 1 << 16,
                 stripe_count: int = 4, object_size: int = 1 << 22,
                 full_stripe_writes: bool = False):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        if stripe_count < 1 or stripe_unit < 1:
            raise ValueError("bad striping parameters")
        self.io = ioctx
        self.su = stripe_unit
        self.sc = stripe_count
        self.osz = object_size
        # r20 routing knob: False (default) sends each piece as a
        # range write (write_at -> the r16 parity-delta/append fast
        # path on EC pools); True forces the pre-r16 full-stripe
        # fallback (read-merge-write_full per piece object) — kept as
        # the A/B baseline the bench amplification cells measure
        # against and as an escape hatch.
        self.full_stripe_writes = bool(full_stripe_writes)
        #: soids this instance knows are DENSE (only ever tail-
        #: appended from empty) — the only streams append() may route
        #: through the server-side-offset rados append op; a sparse
        #: write evicts (server tail != expected piece offset there)
        self._dense: set[str] = set()
        # the size/hwm metadata update is a read-modify-write spanning
        # two ops; concurrent aio writers to one striped object could
        # interleave and lose a size extension. RLock: truncate holds
        # it across its own RMW while its zeroing calls write()
        self._meta_locks: dict[str, threading.RLock] = {}
        self._meta_locks_guard = threading.Lock()

    def _meta_lock(self, soid: str) -> threading.RLock:
        with self._meta_locks_guard:
            return self._meta_locks.setdefault(soid, threading.RLock())

    def _obj(self, soid: str, q: int) -> str:
        return f"{soid}.{q:016x}"

    def _meta(self, soid: str) -> str:
        return f"{soid}.meta"

    def _extents(self, offset: int, length: int):
        """Yield (object index, object offset, logical offset, len)
        pieces covering [offset, offset+length)."""
        units_per_set = self.sc * (self.osz // self.su)
        pos = offset
        end = offset + length
        while pos < end:
            su_idx = pos // self.su
            intra = pos % self.su
            take = min(self.su - intra, end - pos)
            obj_set, in_set = divmod(su_idx, units_per_set)
            obj_in_set = in_set % self.sc
            row = in_set // self.sc          # stripe row within the set
            q = obj_set * self.sc + obj_in_set
            ooff = row * self.su + intra
            yield q, ooff, pos, take
            pos += take

    def piece_extents(self, q: int, upto: int):
        """Logical (offset, len) extents mapping to piece object q,
        clamped to [0, upto) — the inverse of the _extents walk. Lives
        here so ONE class owns the striping geometry (RBD clone
        copy-up and diff depend on it)."""
        rows = self.osz // self.su
        units_per_set = self.sc * rows
        obj_set, obj_in_set = divmod(q, self.sc)
        for row in range(rows):
            unit = obj_set * units_per_set + row * self.sc + obj_in_set
            loff = unit * self.su
            if loff >= upto:
                break
            yield loff, min(self.su, upto - loff)

    def _read_meta(self, soid: str,
                   snap: int | None = None) -> tuple[int, int]:
        """(logical size, high-water-mark size). The hwm tracks the
        LARGEST size the stream ever had, so remove() can find pieces
        a later truncate-shrink left behind (zeroed but extant). Old
        8-byte metas (pre-hwm) read back hwm == size."""
        try:
            raw = bytes(self.io.read(self._meta(soid), snap=snap))
        except KeyError:
            raise KeyError(f"no striped object {soid!r}")
        size = int.from_bytes(raw[:8], "little")
        hwm = int.from_bytes(raw[8:16], "little") if len(raw) >= 16 \
            else size
        return size, max(size, hwm)

    def _write_meta(self, soid: str, size: int, hwm: int,
                    snapc: int = 0) -> None:
        self.io.write_full(self._meta(soid),
                           size.to_bytes(8, "little")
                           + hwm.to_bytes(8, "little"), snapc=snapc)

    def size(self, soid: str, snap: int | None = None) -> int:
        return self._read_meta(soid, snap=snap)[0]

    def write(self, soid: str, data: bytes | np.ndarray,
              offset: int = 0, snapc: int = 0) -> None:
        arr = np.frombuffer(bytes(data), dtype=np.uint8) \
            if isinstance(data, (bytes, bytearray, memoryview)) \
            else np.asarray(data, np.uint8).reshape(-1)
        if self.full_stripe_writes:
            self._write_full_stripe(soid, arr, offset, snapc)
        else:
            for q, ooff, lpos, ln in self._extents(offset, len(arr)):
                piece = arr[lpos - offset:lpos - offset + ln]
                self.io.write(self._obj(soid, q), piece, offset=ooff,
                              snapc=snapc)
        with self._meta_lock(soid):
            try:
                cur, hwm = self._read_meta(soid)
            except KeyError:
                cur = hwm = 0
            if offset > cur:
                # a hole opened below the tail: the stream is no
                # longer dense, append() must stop trusting the
                # server-side tail to equal the computed piece offset
                self._dense.discard(soid)
            new = max(cur, offset + len(arr))
            if new != cur:
                self._write_meta(soid, new, max(hwm, new), snapc=snapc)

    def _write_full_stripe(self, soid: str, arr: np.ndarray,
                           offset: int, snapc: int) -> None:
        """The full-stripe fallback: read-merge-write_full every piece
        object the range touches (each rados write re-encodes the
        whole object — the k+m wire fan-out the r16 delta path
        avoids). Kept selectable so the benches can measure the
        amplification win on the SAME workload."""
        by_obj: dict[int, list] = {}
        for q, ooff, lpos, ln in self._extents(offset, len(arr)):
            by_obj.setdefault(q, []).append((ooff, lpos, ln))
        for q in sorted(by_obj):
            name = self._obj(soid, q)
            try:
                cur = np.frombuffer(self.io.read(name),
                                    dtype=np.uint8)
            except KeyError:
                cur = np.zeros(0, dtype=np.uint8)
            need = max(len(cur),
                       max(ooff + ln for ooff, _, ln in by_obj[q]))
            buf = np.zeros(need, dtype=np.uint8)
            buf[:len(cur)] = cur
            for ooff, lpos, ln in by_obj[q]:
                buf[ooff:ooff + ln] = arr[lpos - offset:
                                          lpos - offset + ln]
            self.io.write_full(name, buf, snapc=snapc)

    def append(self, soid: str, data: bytes | np.ndarray,
               snapc: int = 0) -> int:
        """Tail append on the logical stream; returns the offset the
        bytes landed at. DENSE streams (only ever appended from
        empty by this instance) ride the rados append op — the
        primary resolves each piece's tail server-side and the r16
        append-into-padding fast path skips the pre-read. Streams
        with holes (or inherited from elsewhere) take the plain
        write_at path at the same logical offset, which is equally
        correct and still delta-eligible."""
        arr = np.frombuffer(bytes(data), dtype=np.uint8) \
            if isinstance(data, (bytes, bytearray, memoryview)) \
            else np.asarray(data, np.uint8).reshape(-1)
        with self._meta_lock(soid):
            try:
                cur, hwm = self._read_meta(soid)
            except KeyError:
                cur = hwm = 0
            dense = (cur == 0 and hwm == 0) or soid in self._dense
            if dense and not self.full_stripe_writes:
                for q, ooff, lpos, ln in self._extents(cur, len(arr)):
                    piece = arr[lpos - cur:lpos - cur + ln]
                    self.io.append(self._obj(soid, q), piece,
                                   snapc=snapc)
                self._dense.add(soid)
                new = cur + len(arr)
                self._write_meta(soid, new, max(hwm, new),
                                 snapc=snapc)
            else:
                self.write(soid, arr, offset=cur, snapc=snapc)
            return cur

    def read(self, soid: str, length: int | None = None,
             offset: int = 0, snap: int | None = None) -> bytes:
        total = self.size(soid, snap=snap)
        if length is None:
            length = max(0, total - offset)
        length = min(length, max(0, total - offset))
        out = np.zeros(length, dtype=np.uint8)
        if not length:
            return b""
        cache: dict[str, np.ndarray] = {}
        for q, ooff, lpos, ln in self._extents(offset, length):
            name = self._obj(soid, q)
            if name not in cache:
                try:
                    cache[name] = np.frombuffer(
                        self.io.read(name, snap=snap), dtype=np.uint8)
                except KeyError:
                    cache[name] = np.zeros(0, dtype=np.uint8)
            obj = cache[name]
            piece = obj[ooff:ooff + ln]
            out[lpos - offset:lpos - offset + len(piece)] = piece
        return out.tobytes()

    def truncate(self, soid: str, new_size: int,
                 zero_chunk: int = 1 << 20, snapc: int = 0) -> None:
        """Shrink (or grow) the logical stream. A shrink ZEROES the
        discarded range before dropping the size, so a later re-grow
        reads zeros there, not resurrected bytes (the block-device
        contract; the reference trims/zeroes objects)."""
        if new_size < 0:
            raise ValueError(f"truncate to {new_size} < 0")
        self._dense.discard(soid)   # object tails now exceed the
        #                             logical size; append() must
        #                             compute offsets again
        with self._meta_lock(soid):
            old, hwm = self._read_meta(soid)
            if new_size < old:
                pos = new_size
                while pos < old:
                    n = min(zero_chunk, old - pos)
                    self.write(soid, b"\x00" * n, offset=pos,
                               snapc=snapc)
                    pos += n
            self._write_meta(soid, new_size, max(hwm, new_size),
                             snapc=snapc)

    def remove(self, soid: str, snapc: int = 0) -> None:
        # walk to the HIGH-WATER mark, not the current size: a
        # truncate-shrink keeps (zeroed) pieces past the new boundary
        # that a size-bounded walk would leak forever
        _, hwm = self._read_meta(soid)
        qs = {q for q, _, _, _ in self._extents(0, max(hwm, 1))}
        for q in sorted(qs):
            try:
                self.io.remove(self._obj(soid, q), snapc=snapc)
            except KeyError:
                pass  # sparse stripe: unit never written
        self.io.remove(self._meta(soid), snapc=snapc)
        self._dense.discard(soid)
        with self._meta_locks_guard:
            self._meta_locks.pop(soid, None)
