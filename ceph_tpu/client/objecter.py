"""Objecter — the client-side placement + retry layer.

Rebuild of the reference's client op path (ref: src/osdc/Objecter.cc
op_submit -> _calc_target -> _op_submit: the client computes
object -> PG -> primary OSD from ITS OWN cached OSDMap, sends the op,
and when the cluster has moved on — wrong primary, down OSD, newer
epoch — it refreshes its map, recomputes the target, and RESENDS
without the caller ever noticing; librados ref: src/librados/
IoCtxImpl.cc rados_write/rados_read on top of it).

The sim transport is SimCluster.client_rpc, which behaves like a
primary OSD session: it rejects ops addressed to the wrong primary
with StaleMap (the reference OSD shares its newer map with the
sender) and refuses connections to dead processes (lossy client
connection). All data-plane batching stays intact: a write dict is
grouped per PG and each PG's group is one batched submission."""

from __future__ import annotations

import numpy as np

from ..utils.perf_counters import PerfCountersBuilder


class ObjecterError(RuntimeError):
    pass


class Objecter:
    """Client session against a SimCluster."""

    MAX_ATTEMPTS = 8

    def __init__(self, cluster, inflight_op_bytes: int = 100 << 20):
        import threading
        from ..utils.throttle import Throttle
        self.cluster = cluster
        # SimCluster's PG state is not thread-safe; dispatch serializes
        # under one lock (the reference Objecter likewise holds its
        # rwlock across _op_submit). The throttle is taken OUTSIDE the
        # lock so backpressure applies to concurrent callers.
        # RLock: IoCtx's direct cluster accessors (stat, listings,
        # snap ops, cls execute) serialize through this same lock so
        # aio worker threads can't race them on thread-unsafe PG
        # state; reentrancy lets a cls method or watch callback call
        # back into the client without deadlocking
        self._dispatch_lock = threading.RLock()
        # client-side backpressure (ref: Objecter's op_throttle_bytes /
        # objecter_inflight_op_bytes): payload bytes are charged before
        # dispatch and released after the reply; a flood of writes
        # blocks the caller instead of ballooning memory
        self.op_throttle = Throttle("objecter_bytes", inflight_op_bytes)
        self.perf = (PerfCountersBuilder("objecter")
                     .add_u64_counter("op_send")
                     .add_u64_counter("op_resend")
                     .add_u64_counter("map_refresh")
                     .add_u64_counter("op_degraded",
                                      "reads served through the "
                                      "degraded fast path (primary "
                                      "dead/parked; any-k decode)")
                     .add_u64_counter("throttle_blocked_bytes")
                     .add_time_avg("op_latency",
                                   "submit-to-reply wall time incl. "
                                   "resends")
                     .create_perf_counters())
        self._epoch = -1
        self._primaries: dict[int, int] = {}
        self._refresh()

    # -- map view -----------------------------------------------------------

    def _refresh(self) -> None:
        """Pull the current OSDMap (the MOSDMap subscription analog).
        Under the (reentrant) dispatch lock: the map + pg_num are
        mutated multi-step by splits/autoscale on the driving thread,
        and aio workers must neither read torn state here nor
        interleave the epoch/primaries update pair."""
        with self._dispatch_lock:
            om = self.cluster.osdmap
            self._epoch = om.epoch
            self._primaries = {
                ps: om.pg_to_up_acting_osds(1, ps)[3]
                for ps in range(self.cluster.pg_num)}
        self.perf.inc("map_refresh")

    def _calc_target(self, name: str) -> tuple[int, int]:
        """object -> (ps, primary osd) from the CACHED map view
        (Objecter::_calc_target)."""
        with self._dispatch_lock:
            ps = self.cluster.osdmap.object_to_pg(1, name)[1]
            return ps, self._primaries.get(ps, -1)

    # -- op submission ------------------------------------------------------

    @staticmethod
    def _payload_bytes(kind: str, payload) -> int:
        if kind == "write":
            return sum(len(np.asarray(v, np.uint8).reshape(-1))
                       if not isinstance(v, (bytes, bytearray)) else len(v)
                       for v in payload.values())
        if kind == "write_ranges":
            return sum(len(np.asarray(d, np.uint8).reshape(-1))
                       if not isinstance(d, (bytes, bytearray)) else len(d)
                       for _, _, d in payload)
        if kind == "append":
            _name, data = payload
            return (len(data) if isinstance(data, (bytes, bytearray))
                    else len(np.asarray(data, np.uint8).reshape(-1)))
        return 0  # reads are charged on the reply side in the reference

    def _submit(self, kind: str, ps: int, payload,
                snapc: int = 0) -> object:
        """Send one PG-targeted op; retarget + resend on staleness
        (the while loop is _op_submit's resend-on-new-map path).
        `snapc` is the newest snap id the caller's SnapContext names
        (selfmanaged-snap pools; 0 = no snaps follow this writer)."""
        from ..utils.tracing import span
        cost = self._payload_bytes(kind, payload)
        if cost and not self.op_throttle.get_or_fail(cost):
            self.perf.inc("throttle_blocked_bytes", cost)
            self.op_throttle.get(cost)  # block until in-flight drains
        try:
            with span(f"objecter.{kind}", counters=self.perf,
                      key="op_latency"):
                return self._submit_inner(kind, ps, payload, snapc)
        finally:
            if cost:
                self.op_throttle.put(cost)

    def _submit_inner(self, kind: str, ps: int, payload, snapc: int):
        from ..osd.cluster import StaleMap
        for attempt in range(self.MAX_ATTEMPTS):
            primary = self._primaries.get(ps, -1)
            self.perf.inc("op_send")
            if attempt:
                self.perf.inc("op_resend")
            try:
                with self._dispatch_lock:
                    return self.cluster.client_rpc(
                        primary, self._epoch, kind, ps, payload,
                        snapc=snapc)
            except StaleMap:
                self._refresh()
                if kind == "read":
                    got = self._maybe_degraded_read(ps, payload)
                    if got is not None:
                        return got
        raise ObjecterError(
            f"op on pg {ps} still untargetable after "
            f"{self.MAX_ATTEMPTS} attempts (epoch {self._epoch})")

    def _maybe_degraded_read(self, ps: int, names):
        """Degraded-read fast path (ROADMAP item 3): when the FRESH
        map still offers no serviceable primary — the primary process
        is dead but not yet detected, or the PG is parked in
        peering/WaitUpThru — a read is served immediately from any k
        surviving shards instead of burning the resend budget waiting
        for detection + activation (mutations still wait: they need
        the durable primary path). Returns None when the normal
        retarget should proceed, and falls back to the retry loop if
        the degraded decode itself cannot complete (below min_size)."""
        with self._dispatch_lock:
            primary = self._primaries.get(ps, -1)
            healthy = (0 <= primary < len(self.cluster.alive)
                       and self.cluster.alive[primary]
                       and self.cluster._peer_classify(ps).serviceable)
            if healthy:
                return None            # a plain retarget will do
            try:
                out = self.cluster.degraded_read(ps, names)
            except (ValueError, KeyError) as e:
                if isinstance(e, KeyError):
                    raise              # no such object is definitive
                return None            # not decodable: keep retrying
        self.perf.inc("op_degraded")
        return out

    def write(self, objects: dict[str, bytes | np.ndarray],
              snapc: int = 0) -> None:
        by_pg: dict[int, dict] = {}
        for name, data in objects.items():
            ps, _ = self._calc_target(name)
            by_pg.setdefault(ps, {})[name] = data
        for ps, group in by_pg.items():
            self._submit("write", ps, group, snapc=snapc)

    def write_at(self, name: str, offset: int,
                 data: bytes | np.ndarray, snapc: int = 0) -> None:
        ps, _ = self._calc_target(name)
        self._submit("write_ranges", ps, [(name, offset, data)],
                     snapc=snapc)

    def append(self, name: str, data: bytes | np.ndarray,
               snapc: int = 0) -> int:
        """Tail append — the primary resolves the current object size
        server-side and lands the bytes there (librados rados_append;
        r16's append fast path skips the pre-read when the tail lands
        in stripe padding). Returns the offset the data landed at."""
        ps, _ = self._calc_target(name)
        return self._submit("append", ps, (name, data), snapc=snapc)

    def _by_pg(self, names: list[str]) -> dict[int, list[str]]:
        by_pg: dict[int, list[str]] = {}
        for name in names:
            ps, _ = self._calc_target(name)
            by_pg.setdefault(ps, []).append(name)
        return by_pg

    def remove(self, names: list[str] | str, snapc: int = 0) -> None:
        names_l = [names] if isinstance(names, str) else list(names)
        for ps, group in self._by_pg(names_l).items():
            self._submit("remove", ps, group, snapc=snapc)

    def read(self, names: list[str] | str) -> dict[str, np.ndarray]:
        single = isinstance(names, str)
        names_l = [names] if single else list(names)
        out: dict[str, np.ndarray] = {}
        for ps, group in self._by_pg(names_l).items():
            out.update(self._submit("read", ps, group))
        return out[names] if single else out
