"""librbd-shaped block-image API: striped images, per-image snapshots,
and COW clone layering.

Rebuild of the reference's block-device surface (ref: src/librbd/ —
`rbd create/resize/remove`, Image::{read,write,size}; snapshots:
librbd snap_create/snap_rollback/snap_protect over SELF-MANAGED rados
snaps + per-op SnapContext, ref: src/librbd/Operations.cc,
src/osdc/Objecter snapc plumbing; layering: clone/copy-up/flatten,
ref: src/librbd/io/CopyupRequest.cc, src/cls/rbd clone/children
bookkeeping; python binding shape ref: src/pybind/rbd/rbd.pyx).

Design notes (framework-native, not a transliteration):

* An image IS striped rados objects plus a JSON header object. Image
  snapshots ride the pool's self-managed snap machinery: `snap_create`
  allocates a pool-wide snap id, and every later data write carries
  that id as its SnapContext (`snapc=`), so the OSD COW-preserves
  clones for THIS image's objects only — other images in the pool,
  whose writers name no snaps, are untouched. That is exactly how
  librbd gets per-image snapshots out of one shared pool.
* Clone layering does copy-up at stripe-piece granularity (the
  reference's unit is its rados object; ours is the striper's piece
  object): the invariant is "a piece object existing in the child
  makes the child authoritative for every extent that maps to it".
  Reads of missing pieces fall through to the parent AT ITS SNAP
  (recursively — grandparent chains work); the first write that
  touches a missing piece first materializes it from the parent
  (the CopyupRequest role), then applies the write.
* `diff_iterate` uses the OSD's metadata-only `snap_changed` (SnapSet
  + birth eras) per piece — the fast-diff/object-map role — instead
  of reading and comparing data.

Simplifications vs the reference, disclosed: flatten requires the
clone to have no snapshots of its own (upstream needs the deep-flatten
feature for that case); diff granularity is the stripe piece, not the
byte range; diff with `from_snap=None` reports the CHILD's allocated
extents only (parent-inherited data is the parent's diff).
"""

from __future__ import annotations

import json

from .rados import IoCtx, RadosStriper


class ImageHasSnapshots(ValueError):
    pass


class ImageBusy(ValueError):
    pass


_CHILDREN_OBJ = "rbd_children"    # ref: cls_rbd children directory


class RBD:
    """Image administration (the RBD() role)."""

    def __init__(self, ioctx: IoCtx, stripe_unit: int = 1 << 16,
                 stripe_count: int = 4, object_size: int = 1 << 22,
                 full_stripe_writes: bool = False):
        self.io = ioctx
        self._geom = (stripe_unit, stripe_count, object_size)
        # r20: block IO rides write_at (the r16 partial-stripe fast
        # path on EC pools) by default; True falls back to the
        # read-merge-write_full full-stripe path (the A/B baseline)
        self.full_stripe_writes = bool(full_stripe_writes)

    def _hdr(self, name: str) -> str:
        return f"rbd_header.{name}"

    def create(self, name: str, size: int) -> "Image":
        if size < 0:
            raise ValueError(f"size {size} < 0")
        if self._exists(name):
            raise FileExistsError(f"image {name!r} exists")
        self._save_hdr(name, {"v": 2, "size": size, "snaps": [],
                              "parent": None})
        return Image(self, name)

    # -- header codec (v1 = bare 8-byte size, pre-snapshot rounds) ----------

    def _load_hdr(self, name: str) -> dict:
        raw = self.io.read(self._hdr(name))
        if len(raw) == 8:      # legacy v1 header
            return {"v": 1, "size": int.from_bytes(raw, "little"),
                    "snaps": [], "parent": None}
        return json.loads(raw.decode())

    def _save_hdr(self, name: str, hdr: dict) -> None:
        self.io.write_full(self._hdr(name),
                           json.dumps(hdr, sort_keys=True).encode())

    def _exists(self, name: str) -> bool:
        try:
            self.io.read(self._hdr(name))
            return True
        except KeyError:
            return False

    def list(self) -> list[str]:
        pre = "rbd_header."
        return sorted(n[len(pre):] for n in self.io.list_objects()
                      if n.startswith(pre))

    def remove(self, name: str) -> None:
        hdr = self._load_hdr(name)   # raises KeyError if missing
        if hdr["snaps"]:
            raise ImageHasSnapshots(
                f"image {name!r} has {len(hdr['snaps'])} snapshot(s); "
                "remove them first (rbd: image has snapshots)")
        if hdr["parent"]:
            self._deregister_child(hdr["parent"], name)
        st = RadosStriper(self.io, *self._geom,
                          full_stripe_writes=self.full_stripe_writes)
        try:
            st.remove(f"rbd_data.{name}")
        except KeyError:
            pass  # never written
        self.io.remove(self._hdr(name))

    # -- layering: clone + children directory -------------------------------

    def clone(self, parent_name: str, snap_name: str,
              child_name: str) -> "Image":
        """COW clone of parent@snap (ref: librbd clone; requires the
        snap protected, as upstream — protection is what guarantees
        the parent data a child depends on cannot be trimmed)."""
        phdr = self._load_hdr(parent_name)
        snap = _find_snap(phdr, snap_name)
        if not snap["protected"]:
            raise ValueError(
                f"snap {parent_name!r}@{snap_name!r} is not protected "
                "(rbd: parent snapshot must be protected)")
        if self._exists(child_name):
            raise FileExistsError(f"image {child_name!r} exists")
        self._save_hdr(child_name, {
            "v": 2, "size": snap["size"], "snaps": [],
            "parent": {"image": parent_name, "snap_id": snap["id"],
                       "snap_name": snap_name,
                       "overlap": snap["size"]}})
        self._register_child(
            {"image": parent_name, "snap_id": snap["id"]}, child_name)
        return Image(self, child_name)

    def _children_dir(self) -> dict:
        try:
            return json.loads(self.io.read(_CHILDREN_OBJ).decode())
        except KeyError:
            return {}

    @staticmethod
    def _child_key(parent: dict) -> str:
        return f"{parent['image']}@{parent['snap_id']}"

    def _register_child(self, parent: dict, child: str) -> None:
        d = self._children_dir()
        kids = d.setdefault(self._child_key(parent), [])
        if child not in kids:
            kids.append(child)
        self.io.write_full(_CHILDREN_OBJ,
                           json.dumps(d, sort_keys=True).encode())

    def _deregister_child(self, parent: dict, child: str) -> None:
        d = self._children_dir()
        key = self._child_key(parent)
        kids = [c for c in d.get(key, []) if c != child]
        if kids:
            d[key] = kids
        else:
            d.pop(key, None)
        self.io.write_full(_CHILDREN_OBJ,
                           json.dumps(d, sort_keys=True).encode())

    def list_children(self, parent_name: str,
                      snap_name: str) -> list[str]:
        phdr = self._load_hdr(parent_name)
        snap = _find_snap(phdr, snap_name)
        return sorted(self._children_dir().get(
            self._child_key({"image": parent_name,
                             "snap_id": snap["id"]}), []))


def _find_snap(hdr: dict, snap_name: str) -> dict:
    for s in hdr["snaps"]:
        if s["name"] == snap_name:
            return s
    raise KeyError(f"no snap {snap_name!r}")


def _snap_by_id(hdr: dict, sid: int) -> dict:
    for s in hdr["snaps"]:
        if s["id"] == sid:
            return s
    raise KeyError(f"no snap id {sid}")


class Image:
    """One open image (the Image() role): bounds-checked random-access
    byte I/O, snapshots, and clone-aware reads/writes."""

    def __init__(self, rbd: RBD, name: str):
        self.rbd = rbd
        self.name = name
        su, sc, osz = rbd._geom
        self._striper = RadosStriper(
            rbd.io, stripe_unit=su, stripe_count=sc, object_size=osz,
            full_stripe_writes=rbd.full_stripe_writes)
        self._soid = f"rbd_data.{name}"
        self._at_snap: int | None = None   # set_snap read mode
        self._pcache: dict[tuple, "Image"] = {}   # parent-at-snap
        self._hdr()  # existence check

    # -- header state -------------------------------------------------------

    def _hdr(self) -> dict:
        return self.rbd._load_hdr(self.name)

    def _save(self, hdr: dict) -> None:
        self.rbd._save_hdr(self.name, hdr)

    def _snapc(self, hdr: dict | None = None) -> int:
        """Newest image snap id = the SnapContext every data write of
        this image carries (0: no snaps, writes preserve nothing)."""
        snaps = (hdr or self._hdr())["snaps"]
        return max((s["id"] for s in snaps), default=0)

    def size(self) -> int:
        hdr = self._hdr()
        if self._at_snap is not None:
            return _snap_by_id(hdr, self._at_snap)["size"]
        return hdr["size"]

    def parent_info(self) -> tuple[str, str, int] | None:
        """(parent image, parent snap name, overlap) or None."""
        p = self._hdr()["parent"]
        return (p["image"], p["snap_name"], p["overlap"]) if p else None

    def resize(self, new_size: int) -> None:
        """Grow or shrink. A shrink really discards the bytes past the
        boundary (striper truncate zeroes them), so a later re-grow
        reads zeros there — the block-device contract."""
        self._check_writable()
        if new_size < 0:
            raise ValueError(f"size {new_size} < 0")
        hdr = self._hdr()
        if new_size < hdr["size"]:
            # a shrink's zero-writes can CREATE a previously missing
            # boundary piece; for a clone that piece must be copied up
            # first or its sub-extents below new_size would become
            # child-authoritative zeros over parent data
            if hdr["parent"]:
                self._copy_up(hdr, new_size, hdr["size"] - new_size)
            try:
                self._striper.truncate(self._soid, new_size,
                                       snapc=self._snapc(hdr))
            except KeyError:
                pass  # nothing ever written; nothing to discard
            # a shrink below the parent overlap permanently narrows it
            # (ref: librbd shrink trims parent_overlap). Snapshots keep
            # their own recorded overlap (per-snap, as librbd does).
            if hdr["parent"] and new_size < hdr["parent"]["overlap"]:
                hdr["parent"]["overlap"] = new_size
        hdr["size"] = new_size
        self._save(hdr)

    def _check_writable(self) -> None:
        if self._at_snap is not None:
            raise ValueError("image is set to a snapshot (read-only); "
                             "set_snap(None) first")

    # -- snapshots ----------------------------------------------------------

    def set_snap(self, snap_name: str | None) -> None:
        """Route reads to the image's state at the snap (librbd
        set_snap); None returns to the live head."""
        if snap_name is None:
            self._at_snap = None
            return
        self._at_snap = _find_snap(self._hdr(), snap_name)["id"]

    def snap_create(self, snap_name: str) -> int:
        self._check_writable()
        hdr = self._hdr()
        if any(s["name"] == snap_name for s in hdr["snaps"]):
            raise FileExistsError(f"snap {snap_name!r} exists")
        sid = self.rbd.io.selfmanaged_snap_create()
        snap = {"id": sid, "name": snap_name,
                "size": hdr["size"], "protected": False}
        if hdr["parent"]:
            # each snap records the parent overlap AS OF the snap
            # (librbd keeps per-snapshot parent info): a later shrink
            # narrows only the head's overlap, not history's
            snap["overlap"] = min(hdr["parent"]["overlap"],
                                  hdr["size"])
        hdr["snaps"].append(snap)
        self._save(hdr)
        return sid

    def snap_list(self) -> list[dict]:
        return [dict(s) for s in self._hdr()["snaps"]]

    def snap_protect(self, snap_name: str) -> None:
        hdr = self._hdr()
        _find_snap(hdr, snap_name)["protected"] = True
        self._save(hdr)

    def snap_unprotect(self, snap_name: str) -> None:
        hdr = self._hdr()
        snap = _find_snap(hdr, snap_name)
        kids = self.rbd.list_children(self.name, snap_name)
        if kids:
            raise ImageBusy(
                f"snap {snap_name!r} has {len(kids)} clone child(ren) "
                f"({', '.join(kids)}); flatten or remove them first")
        snap["protected"] = False
        self._save(hdr)

    def snap_is_protected(self, snap_name: str) -> bool:
        return bool(_find_snap(self._hdr(), snap_name)["protected"])

    def snap_remove(self, snap_name: str) -> None:
        hdr = self._hdr()
        snap = _find_snap(hdr, snap_name)
        if snap["protected"]:
            raise ImageBusy(f"snap {snap_name!r} is protected")
        self.rbd.io.selfmanaged_snap_remove(snap["id"])
        hdr["snaps"] = [s for s in hdr["snaps"]
                        if s["id"] != snap["id"]]
        self._save(hdr)

    def snap_rollback(self, snap_name: str) -> None:
        """Write the snap's state back onto the head (librbd
        snap_rollback). The rollback writes themselves carry the
        newest snapc, so the pre-rollback head stays readable at any
        newer snap."""
        self._check_writable()
        hdr = self._hdr()
        snap = _find_snap(hdr, snap_name)
        # capture the snap's full state (clone-aware, at-snap)
        prev = self._at_snap
        self._at_snap = snap["id"]
        try:
            data = self.read(0, snap["size"])
        finally:
            self._at_snap = prev
        self.resize(snap["size"])
        if data:
            self.write(0, data)

    # -- data path ----------------------------------------------------------

    def write(self, offset: int, data: bytes) -> int:
        self._check_writable()
        hdr = self._hdr()
        end = offset + len(data)
        if offset < 0 or end > hdr["size"]:
            raise ValueError(
                f"write [{offset}, {end}) outside image size "
                f"{hdr['size']}")
        if not data:
            return 0
        if hdr["parent"]:
            self._copy_up(hdr, offset, len(data))
        self._striper.write(self._soid, data, offset=offset,
                            snapc=self._snapc(hdr))
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        hdr = self._hdr()
        size = _snap_by_id(hdr, self._at_snap)["size"] \
            if self._at_snap is not None else hdr["size"]
        if offset < 0 or offset > size:
            raise ValueError(f"read offset {offset} outside size {size}")
        length = min(length, size - offset)
        if length <= 0:
            return b""
        if hdr["parent"]:
            return self._clone_read(hdr, offset, length)
        got = self._plain_read(offset, length)
        # sparse regions (never written) read as zeros, like a block dev
        return got.ljust(length, b"\x00")

    def _plain_read(self, offset: int, length: int) -> bytes:
        try:
            return self._striper.read(self._soid, length=length,
                                      offset=offset, snap=self._at_snap)
        except KeyError:
            return b""  # nothing written yet

    # -- clone layering internals -------------------------------------------

    def _piece_exists(self, q: int) -> bool:
        name = self._striper._obj(self._soid, q)
        try:
            if self._at_snap is None:
                self.rbd.io.stat(name)
            else:
                self.rbd.io.read(name, length=0, snap=self._at_snap)
            return True
        except KeyError:
            return False

    def _parent_image(self, hdr: dict) -> "Image":
        """Open (and cache) the parent at its clone snap. Caching is
        safe: a parent-at-snap is immutable while children exist (the
        snap is protected, and flatten refuses on an image that still
        has snaps), so one existence check per child Image suffices."""
        p = hdr["parent"]
        key = (p["image"], p["snap_id"])
        parent = self._pcache.get(key)
        if parent is None:
            parent = Image(self.rbd, p["image"])
            parent._at_snap = p["snap_id"]
            self._pcache[key] = parent
        return parent

    def _clone_read(self, hdr: dict, offset: int, length: int) -> bytes:
        """Per-piece: child piece exists -> child is authoritative;
        missing piece -> parent-at-snap serves extents inside the
        overlap, zeros beyond (ref: librbd io::ImageReadRequest parent
        fall-through)."""
        p = hdr["parent"]
        parent = self._parent_image(hdr)
        if self._at_snap is not None:
            # at-snap reads honor the overlap recorded AT that snap,
            # not the head's (which later shrinks may have narrowed)
            snap = _snap_by_id(hdr, self._at_snap)
            overlap = snap.get("overlap", p["overlap"])
        else:
            overlap = p["overlap"]
        out = bytearray(length)
        exists: dict[int, bool] = {}
        # coalesce consecutive same-source extents into ranged reads:
        # a striped read otherwise issues one parent/child call PER
        # stripe unit, each re-reading headers down the parent chain
        runs: list[list] = []       # [from_child, start, len]
        for q, ooff, lpos, ln in self._striper._extents(offset, length):
            if q not in exists:
                exists[q] = self._piece_exists(q)
            src = exists[q]
            if not src and lpos >= overlap:
                continue            # missing piece past overlap: zeros
            take = ln if src else min(ln, overlap - lpos)
            if runs and runs[-1][0] == src \
                    and runs[-1][1] + runs[-1][2] == lpos:
                runs[-1][2] += take
            else:
                runs.append([src, lpos, take])
        for from_child, start, ln in runs:
            got = self._plain_read(start, ln) if from_child \
                else parent.read(start, ln)
            out[start - offset:start - offset + len(got)] = got
        return bytes(out)

    def _piece_extents(self, q: int, upto: int):
        return self._striper.piece_extents(q, upto)

    def _copy_up(self, hdr: dict, offset: int, length: int) -> None:
        """Materialize every missing piece the write will touch from
        the parent (ref: librbd io::CopyupRequest): after this, the
        child is authoritative for those pieces and the plain striper
        write may proceed."""
        p = hdr["parent"]
        overlap = min(p["overlap"], hdr["size"])
        parent = self._parent_image(hdr)
        snapc = self._snapc(hdr)
        touched = {q for q, _, _, _ in
                   self._striper._extents(offset, length)}
        for q in sorted(touched):
            if self._piece_exists(q):
                continue
            for loff, ln in self._piece_extents(q, overlap):
                got = parent.read(loff, ln)
                self._striper.write(self._soid, got, offset=loff,
                                    snapc=snapc)

    def flatten(self) -> None:
        """Copy every still-inherited piece up from the parent, then
        sever the parent link (librbd flatten). Requires the clone to
        have no snapshots of its own (upstream needs the deep-flatten
        feature for that; disclosed simplification)."""
        self._check_writable()
        hdr = self._hdr()
        p = hdr["parent"]
        if p is None:
            return
        if hdr["snaps"]:
            raise ImageHasSnapshots(
                "flatten with own snapshots needs deep-flatten; "
                "remove the clone's snapshots first")
        overlap = min(p["overlap"], hdr["size"])
        if overlap:
            self._copy_up(hdr, 0, overlap)
        hdr["parent"] = None
        self._save(hdr)
        self.rbd._deregister_child(p, self.name)

    # -- incremental export/import (rbd export-diff / import-diff) ----------

    def export_diff(self, from_snap: str | None = None) -> bytes:
        """Serialize the extents that changed since `from_snap` (None:
        every allocated extent — a full export-diff) into a versioned
        blob import_diff applies (ref: src/tools/rbd/action/
        ExportDiff.cc stream format role: header + sized extent
        records)."""
        from ..utils.encoding import Encoder
        if self._at_snap is not None:
            # diff_iterate pins the head view; mixing at-snap reads
            # with head-derived runs would serialize an inconsistent
            # stream (or fault past the snap size)
            raise ValueError("export_diff operates on the live head; "
                             "set_snap(None) first")
        hdr = self._hdr()
        if hdr["parent"] and from_snap is None:
            # a FULL export of a clone must include parent-inherited
            # data (diff_iterate reports child-materialized pieces
            # only): union the allocated pieces of EVERY layer down
            # the parent chain (clipped to each overlap) instead of
            # serializing the whole image — sparse clones stay sparse
            # in the stream
            runs = self._exported_runs(hdr, hdr["size"])
        else:
            runs = self.diff_iterate(from_snap=from_snap)
        e = Encoder().start(1, 1)
        e.string(from_snap or "")
        e.u64(hdr["size"])
        e.u32(len(runs))
        for off, ln in runs:
            e.u64(off).blob(self.read(off, ln))
        return e.finish().bytes()

    def _exported_runs(self, hdr: dict, upto: int) -> list[tuple]:
        """Merged (offset, len) runs where data may exist for this
        image view: own allocated pieces plus, for clones, the parent
        chain's allocated pieces clipped to the overlap."""
        runs: list[tuple[int, int]] = []
        if upto:
            pieces = {q for q, _, _, _ in
                      self._striper._extents(0, upto)}
            for q in sorted(pieces):
                if self._piece_exists(q):
                    runs.extend(self._piece_extents(q, upto))
        p = hdr["parent"]
        if p is not None:
            parent = self._parent_image(hdr)
            ov = min(p["overlap"], upto)
            runs.extend(parent._exported_runs(parent._hdr(), ov))
        runs.sort()
        merged: list[tuple[int, int]] = []
        for off, ln in runs:
            if merged and off <= merged[-1][0] + merged[-1][1]:
                end = max(merged[-1][0] + merged[-1][1], off + ln)
                merged[-1] = (merged[-1][0], end - merged[-1][0])
            else:
                merged.append((off, ln))
        return merged

    def import_diff(self, blob: bytes) -> int:
        """Apply an export-diff stream: the from-snap (when the stream
        names one) must exist on THIS image — the same continuity
        check `rbd import-diff` enforces, or an incremental chain
        applied out of order silently corrupts. Returns bytes
        written."""
        from ..utils.encoding import Decoder
        self._check_writable()
        d = Decoder(blob)
        d.start(1)
        from_snap = d.string()
        size = d.u64()
        n = d.u32()
        if from_snap:
            _find_snap(self._hdr(), from_snap)   # KeyError: broken chain
        if self.size() != size:
            self.resize(size)
        written = 0
        for _ in range(n):
            off = d.u64()
            data = d.blob()
            self.write(off, bytes(data))
            written += len(data)
        d.finish()
        return written

    # -- diff ---------------------------------------------------------------

    def diff_iterate(self, from_snap: str | None = None) -> list[tuple]:
        """Changed extents since `from_snap` (None: allocated extents),
        at stripe-piece granularity, as (offset, length) sorted merged
        runs. Uses the OSD's metadata-only snap_changed — the
        fast-diff role; no data is read. Always computed against the
        live HEAD — a set_snap read mode is ignored for the duration
        (mixing at-snap existence probes with head sizing would yield
        an extent set that is neither view)."""
        hdr = self._hdr()
        size = hdr["size"]
        if not size:
            return []
        from_sid = _find_snap(hdr, from_snap)["id"] if from_snap \
            else None
        changed: list[tuple[int, int]] = []
        pieces = {q for q, _, _, _ in self._striper._extents(0, size)}
        prev_at_snap, self._at_snap = self._at_snap, None
        try:
            for q in sorted(pieces):
                name = self._striper._obj(self._soid, q)
                if from_sid is not None:
                    # snap_changed returns False for never-written
                    # names; it raises only for an UNKNOWN snap id — a
                    # real header/pool desync that must surface, not
                    # be swallowed as "empty diff"
                    dirty = self.rbd.io.snap_changed(name, from_sid)
                else:
                    dirty = self._piece_exists(q)
                if dirty:
                    changed.extend(self._piece_extents(q, size))
        finally:
            self._at_snap = prev_at_snap
        changed.sort()
        # merge adjacent runs for a compact diff
        merged: list[tuple[int, int]] = []
        for off, ln in changed:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((off, ln))
        return merged
