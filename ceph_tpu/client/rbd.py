"""librbd-shaped block-image API over the striper.

Rebuild of the reference's block-device surface shape (ref:
src/librbd/ — `rbd create/resize/remove`, Image::{read,write,size};
python binding shape ref: src/pybind/rbd/rbd.pyx RBD()/Image()). An
RBD image IS striped rados objects plus a small header recording
size/order — exactly what RadosStriper already provides — so this
layer is deliberately thin: naming, header bookkeeping, bounds
checking, resize semantics. Snapshots/clones/journaling are out of the
target slice (SURVEY.md marks L8 services as context).

Layout compatibility note: the reference stores data objects as
`rbd_data.<id>.<object_no:016x>` with one object per object_size span;
here objects are the striper's `<name>.<q:016x>` pieces with
stripe_unit round-robin (the reference supports the same fancy
striping via --stripe-unit/--stripe-count).
"""

from __future__ import annotations

from .rados import IoCtx, RadosStriper


class RBD:
    """Image administration (the RBD() role)."""

    def __init__(self, ioctx: IoCtx, stripe_unit: int = 1 << 16,
                 stripe_count: int = 4, object_size: int = 1 << 22):
        self.io = ioctx
        self._geom = (stripe_unit, stripe_count, object_size)

    def _hdr(self, name: str) -> str:
        return f"rbd_header.{name}"

    def create(self, name: str, size: int) -> "Image":
        if size < 0:
            raise ValueError(f"size {size} < 0")
        if self._exists(name):
            raise FileExistsError(f"image {name!r} exists")
        self.io.write_full(self._hdr(name),
                           size.to_bytes(8, "little"))
        return Image(self, name)

    def _exists(self, name: str) -> bool:
        try:
            self.io.read(self._hdr(name))
            return True
        except KeyError:
            return False

    def list(self) -> list[str]:
        pre = "rbd_header."
        return sorted(n[len(pre):] for n in self.io.list_objects()
                      if n.startswith(pre))

    def remove(self, name: str) -> None:
        img = Image(self, name)  # raises if missing
        st = img._striper
        try:
            st.remove(f"rbd_data.{name}")
        except KeyError:
            pass  # never written
        self.io.remove(self._hdr(name))


class Image:
    """One open image (the Image() role): bounds-checked random-access
    byte I/O over the striped data objects."""

    def __init__(self, rbd: RBD, name: str):
        self.rbd = rbd
        self.name = name
        su, sc, osz = rbd._geom
        self._striper = RadosStriper(rbd.io, stripe_unit=su,
                                     stripe_count=sc, object_size=osz)
        self._soid = f"rbd_data.{name}"
        self.size()  # existence check

    def size(self) -> int:
        return int.from_bytes(self.rbd.io.read(
            self.rbd._hdr(self.name)), "little")

    def resize(self, new_size: int) -> None:
        """Grow or shrink. A shrink really discards the bytes past the
        boundary (striper truncate zeroes them), so a later re-grow
        reads zeros there — the block-device contract."""
        if new_size < 0:
            raise ValueError(f"size {new_size} < 0")
        if new_size < self.size():
            try:
                self._striper.truncate(self._soid, new_size)
            except KeyError:
                pass  # nothing ever written; nothing to discard
        self.rbd.io.write_full(self.rbd._hdr(self.name),
                               new_size.to_bytes(8, "little"))

    def write(self, offset: int, data: bytes) -> int:
        end = offset + len(data)
        if offset < 0 or end > self.size():
            raise ValueError(
                f"write [{offset}, {end}) outside image size "
                f"{self.size()}")
        self._striper.write(self._soid, data, offset=offset)
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        size = self.size()
        if offset < 0 or offset > size:
            raise ValueError(f"read offset {offset} outside size {size}")
        length = min(length, size - offset)
        if length <= 0:
            return b""
        got = self._striper_read(offset, length)
        # sparse regions (never written) read as zeros, like a block dev
        return got.ljust(length, b"\x00")

    def _striper_read(self, offset: int, length: int) -> bytes:
        try:
            return self._striper.read(self._soid, length=length,
                                      offset=offset)
        except KeyError:
            return b""  # nothing written yet
