"""Chaos engineering for the wire tier: the seeded Thrasher (the
teuthology OSDThrasher role) and its invariant checkers."""

from .thrasher import (KNOBS, InvariantViolation, Thrasher,  # noqa: F401
                       load_factor, repro_command)
