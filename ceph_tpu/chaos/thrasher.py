"""Thrasher — a deterministic, seed-driven fault scheduler for the
wire tier (the teuthology OSDThrasher role, ref: qa/tasks/
ceph_manager.py: random kill/revive/injection during live I/O, then
assert the cluster converged and nothing was lost).

Design goals, in order:

1. REPRODUCIBLE. Every decision — which fault, which victim, what
   data, which injection knob values — is drawn from ONE
   `random.Random(seed)`. The messenger injection knobs are seeded
   per daemon (`Messenger.seed_injection`), so a logged seed replays
   the same fault schedule and the same delay draws. Thread
   interleaving still varies run to run (real sockets, real
   threads), which is the point: the schedule is the experiment, the
   nondeterministic execution is the population it samples.
2. COMPOSED. Faults run with cephx tickets AND secure (encrypted)
   frames on, over either store backend ("mem"/"tin"), with
   `ms_inject_socket_failures` + `ms_inject_delay` live on every
   daemon and scheduled scrub enabled — the full production-shaped
   stack, not an isolated knob (round 5's messenger identity bugs
   only surfaced under exactly this composition).
3. CHECKED. After every round's heal the invariants run:
     * convergence   — every PG's primary hosts a caught-up backend
                       (wait_for_clean);
     * exactly-once  — every acked write reads back byte-exact, every
       bytes           acked overwrite reads the LAST acked value;
     * no            — an acked remove stays removed (a rejoined
       resurrection    shard's stale copy must never come back);
   and at teardown:
     * fsck-clean    — every TinStore directory (the stores crashed
       remount         mid-chaos and remounted, then died with the
                       final shutdown) passes offline fsck with zero
                       errors.
   An invariant failure raises InvariantViolation carrying the seed
   and the one-command reproducer (`tools/thrash.py --seed N ...`).

Client ops that fail mid-chaos (PG below min_size, primary pre-
active, quorum loss) are PARKED, not errors: the op's target object
moves to the `unknown` set and is excluded from exactly-once /
resurrection claims — an op whose ack never arrived proves nothing
either way (the reference's thrasher tolerates EAGAIN the same way).
"""

from __future__ import annotations

import random
import time


def load_factor(cap: float = 4.0) -> float:
    """How oversubscribed this host is right now (1-min loadavg per
    core, floored at 1, capped). Deadline scaling for timing-sensitive
    cells: convergence/heartbeat budgets tuned on an idle box flake
    under full-suite load (CHANGES r10: matrix cell [41-tin] and the
    standalone leader-failover case pass alone, fail only under load)
    — scaling the DEADLINE by the observed load keeps the assertion
    meaningful on both."""
    import os
    try:
        la = os.getloadavg()[0]
    except (OSError, AttributeError):
        return 1.0
    cpus = os.cpu_count() or 1
    return max(1.0, min(cap, la / cpus))

#: the fault menu — name -> (weight, description). `--list-knobs`
#: prints this; the weights are part of the schedule contract (a seed
#: replays the same draws only against the same menu).
KNOBS: dict[str, tuple[int, str]] = {
    "write": (4, "write fresh objects through the client"),
    "overwrite": (2, "rewrite a previously-named object (exactly-once "
                     "check tracks the last acked value)"),
    "remove": (2, "remove an object (no-resurrection check)"),
    "kill_osd": (2, "SIGKILL an OSD (budget: <= m concurrently dead)"),
    "revive_osd": (2, "revive a killed OSD (TinStore: WAL remount)"),
    "remount": (1, "kill + immediately revive one OSD — a pure "
                   "store-remount cycle"),
    "socket_failures": (1, "re-seed ms_inject_socket_failures with a "
                           "drawn period on every live daemon"),
    "delays": (1, "re-seed ms_inject_delay with drawn period/max_ms"),
    "mon_kill": (1, "SIGKILL a monitor (may take out the majority — "
                    "map mutations and activation stall)"),
    "mon_revive": (1, "revive a killed monitor (store sync + "
                      "election)"),
    "deep_scrub": (1, "client-driven deep scrub of a random PG "
                      "(scheduled scrub also runs throughout via "
                      "osd_scrub_interval)"),
}


def repro_command(seed: int, store: str, rounds: int, ops: int,
                  op_shards: int = 1, osd_procs: bool = False,
                  rotate_secrets: bool = False,
                  overwrite_during_faults: bool = False,
                  transient_fraction: float = 0.0,
                  workload_profile: str | None = None,
                  disk_full: bool = False,
                  link_degrade: bool = False) -> str:
    """The one-command local reproduction for a failing cell."""
    cmd = (f"python tools/thrash.py --seed {seed} --store {store} "
           f"--rounds {rounds} --ops {ops}")
    if op_shards != 1:
        cmd += f" --op-shards {op_shards}"
    if osd_procs:
        cmd += " --osd-procs"
    if rotate_secrets:
        cmd += " --rotate-secrets"
    if overwrite_during_faults:
        cmd += " --overwrite-during-faults"
    if transient_fraction:
        cmd += f" --transient-fraction {transient_fraction}"
    if workload_profile:
        cmd += f" --workload-profile {workload_profile}"
    if disk_full:
        cmd += " --disk-full"
    if link_degrade:
        cmd += " --link-degrade"
    return cmd


class InvariantViolation(AssertionError):
    """An invariant failed; the message carries seed + reproducer."""

    def __init__(self, what: str, seed: int, repro: str):
        super().__init__(
            f"{what}\n  thrash seed: {seed}\n  reproduce: {repro}")
        self.seed = seed
        self.repro = repro


class Thrasher:
    """One seeded thrash run over a StandaloneCluster."""

    def __init__(self, seed: int, store: str = "mem", rounds: int = 2,
                 ops: int = 6, n_osds: int = 4, pg_num: int = 2,
                 store_dir: str | None = None, verbose: bool = False,
                 read_during_faults: bool = False,
                 op_shards: int = 1, osd_procs: bool = False,
                 rotate_secrets: bool = False,
                 overwrite_during_faults: bool = False,
                 transient_fraction: float = 0.0,
                 profile: str | None = None,
                 workload_profile: str | None = None,
                 disk_full: bool = False,
                 link_degrade: bool = False):
        self.seed = int(seed)
        self.store = store
        self.rounds = rounds
        self.ops = ops
        self.n_osds = n_osds
        self.pg_num = pg_num
        self.store_dir = store_dir
        self.verbose = verbose
        # mid-fault read sweep (degraded-read invariant): every acked
        # object must read back bit-exact BEFORE the round heals —
        # i.e. no read ever blocks on wait_for_clean. Off by default
        # so the seed-pinned matrix cells keep their timing profile.
        self.read_during_faults = read_during_faults
        self.degraded_read_checks = 0
        # r13: osd_op_num_shards under chaos — ops hash by PG to
        # per-shard mClock queues; the exactly-once/no-resurrection
        # invariants must hold under sharded dispatch too
        self.op_shards = int(op_shards)
        # r15: every OSD in its own OS process (multiproc.py); forces
        # a real on-disk store so SIGKILL+revive survives the process
        # boundary, and routes the RAM-reaching helpers (rotation
        # push, store fsck) over the new control lines
        self.osd_procs = bool(osd_procs)
        if self.osd_procs:
            self.store = store = "tin"
        # deterministic per-round secret rotation (OUTSIDE the seeded
        # action menu, so existing seed-pinned cells replay unchanged):
        # rotate at every heal; live daemons — child processes
        # included — must keep serving through the keep-window
        self.rotate_secrets = bool(rotate_secrets)
        # r16: partial overwrites WITH the round's faults still live —
        # SIGKILL lands mid-RMW, exercising the stripe journal's
        # replay under the exactly-once/no-resurrection checkers. Like
        # rotate_secrets, the sweep draws from its OWN seeded stream
        # (OUTSIDE the action menu) so pinned cells replay unchanged.
        self.overwrite_during_faults = bool(overwrite_during_faults)
        self.rmw_rng = random.Random(self.seed ^ 0x5EED)
        self.rmw_overwrite_checks = 0
        # r17: transient-vs-real failure mix — a seeded fraction of
        # extra kills AUTO-REVIVE inside or outside the repair delay
        # window, exercising the lazy-repair policy under chaos. The
        # sweep draws from its OWN stream (OUTSIDE the action menu,
        # like rmw_rng) so pinned cells replay unchanged; victims are
        # tracked apart from dead_osds so the menu's draws stay
        # schedule-deterministic. Requires in-process daemons (the
        # invariant checkers read policy counters from daemon RAM).
        self.transient_fraction = float(transient_fraction)
        self.profile = profile
        # r20: a seeded tenant-profile op burst rides each round's
        # fault window — the workload engine's stream generator
        # (ceph_tpu.workload) keyed on (profile, seed ^ round), so
        # the burst is fully deterministic and, like rmw_rng, lives
        # OUTSIDE the action menu: pinned cells replay unchanged
        # when the flag is off
        self.workload_profile = workload_profile
        self.workload_ops = 0
        # r21: the disk_full fault stream — capacity-exhaustion
        # windows (every live store shrunk to just over the failsafe
        # ratio, mon ladder flips FULL, a background writer must PARK
        # with zero op_errors and drain exactly-once after restore)
        # plus one-shot ENOSPC injection at a drawn store txn phase
        # each round. Own stream (OUTSIDE the action menu, like
        # rmw_rng): pinned cells replay unchanged with the flag off.
        # In-process only: the sweep reaches stores and perf counters
        # through daemon RAM.
        self.disk_full = bool(disk_full)
        self.full_rng = random.Random(self.seed ^ 0xF011)
        self.full_windows = 0
        self.full_reads_served = 0
        self.full_parked_drained = 0
        self.enospc_injected = 0
        self.enospc_fired = 0
        #: armed one-shot ENOSPC faults: (osd, phase, {"n": shots})
        self._armed_faults: list[tuple[int, str, dict]] = []
        # r22: the link_degrade fault stream — one directed-link
        # degrade window per round against the HEALED cluster: a drawn
        # one-way delay+jitter on exactly one sender->peer edge, and
        # the netobs plane must (a) flip OSD_SLOW_PING_TIME naming
        # exactly that link within two grace windows, (b) reprice the
        # degraded peer worst in the sender's helper-cost feed
        # (counter-pinned on net_helper_penalties), (c) clear after
        # heal. Own stream (OUTSIDE the action menu, like rmw_rng):
        # pinned cells replay unchanged with the flag off. In-process
        # only (the window reads link trackers and perf counters from
        # daemon RAM).
        self.link_degrade = bool(link_degrade)
        self.link_rng = random.Random(self.seed ^ 0x11CD)
        self.link_windows = 0
        self.link_health_flips = 0
        self.link_health_clears = 0
        self.link_repriced = 0
        self.trans_rng = random.Random(self.seed ^ 0x7AB5)
        # victim -> (revive deadline, inside_window, quiet_start,
        #            kill schedule idx, repair-bytes snapshot at kill)
        self.transient_dead: dict[int, tuple] = {}
        self.transient_kills = 0
        self.transient_revives_inside = 0
        self.transient_noop_checks = 0
        self.transient_noop_skips = 0
        # deadline scaling, NOT schedule input: the RNG stream never
        # sees it, so a seed replays identically on an idle box.
        # self.load is the CONSTRUCTION-TIME sample — it pins the
        # config the daemons run under (op_timeout, hb_grace,
        # osd_repair_delay) so those stay stable for the whole run.
        # Wait-site deadlines re-sample via _load() instead (r22
        # deflake): a full-suite run's load ramps over minutes, and a
        # deadline scaled by a stale sample taken at construction
        # under-budgets the waits that actually hit the loaded phase.
        self.load = load_factor()
        # wall seconds of the r17 repair delay the transient cells run
        # under (load-scaled at execution, never an RNG input)
        self.repair_delay = 5.0 * self.load
        self.rng = random.Random(self.seed)
        # shadow state (the invariant oracles)
        self.shadow: dict[str, bytes] = {}   # name -> last ACKED bytes
        self.removed: set[str] = set()       # ACKED removes
        self.unknown: set[str] = set()       # un-acked fate: no claims
        self.dead_osds: set[int] = set()
        self.dead_mons: set[int] = set()
        self.schedule: list[str] = []        # the replayable fault log
        self._obj_i = 0
        self.repro = repro_command(
            self.seed, self.store, rounds, ops,
            op_shards=self.op_shards, osd_procs=self.osd_procs,
            rotate_secrets=self.rotate_secrets,
            overwrite_during_faults=self.overwrite_during_faults,
            transient_fraction=self.transient_fraction,
            workload_profile=self.workload_profile,
            disk_full=self.disk_full,
            link_degrade=self.link_degrade)
        self.c = None
        self.cl = None

    # -- plumbing ------------------------------------------------------------

    def _load(self) -> float:
        """Fresh load sample for a WAIT-SITE deadline (never for
        config, never for an RNG stream): at least the construction
        sample, so a deadline never shrinks mid-run below what the
        daemons' own load-pinned config was budgeted for."""
        return max(self.load, load_factor())

    def _log(self, msg: str) -> None:
        self.schedule.append(msg)
        # every fault event ALSO rides the gathered log ring with the
        # seed stamped in, so `ceph daemon <name> log dump` over the
        # admin socket reconstructs the fault timeline mid-chaos —
        # interleaved with the daemons' own events in one clock
        from ..utils.log import dout
        dout("chaos", 1, f"thrash seed={self.seed} {msg}")
        if self.verbose:
            print(f"thrash[{self.seed}]: {msg}", flush=True)

    def _violate(self, what: str) -> None:
        raise InvariantViolation(what, self.seed, self.repro)

    def _fresh_names(self, n: int) -> list[str]:
        names = [f"thrash-{self.seed}-{self._obj_i + j}"
                 for j in range(n)]
        self._obj_i += n
        return names

    def _parked(self, what: str, e: Exception) -> None:
        self._log(f"parked {what}: {type(e).__name__}")

    # -- setup / teardown ----------------------------------------------------

    def setup(self):
        from ..osd.standalone import StandaloneCluster
        # cephx + secure ON: the secret is seed-derived so even the
        # key schedule replays; tin gets a real on-disk directory
        secret = bytes(self.rng.randrange(256) for _ in range(32))
        self._log(f"setup n_osds={self.n_osds} pg_num={self.pg_num} "
                  f"store={self.store} cephx+secure on")
        kwargs = {}
        if self.profile is not None:
            kwargs["profile"] = self.profile
        self.c = StandaloneCluster(
            n_osds=self.n_osds, pg_num=self.pg_num, store=self.store,
            store_dir=self.store_dir, cephx=True, secret=secret,
            # op_timeout scales too (r19 deflake): a 6s budget tuned
            # idle let in-flight ops time out under full-suite load
            # and read as transient-smoke failures [311]
            op_timeout=6.0 * self.load, op_shards=self.op_shards,
            osd_procs=self.osd_procs,
            # a loaded host stretches every ping round trip: scale the
            # grace with the observed load so CPU starvation doesn't
            # read as daemon death (the [41-tin] full-suite flake)
            hb_grace=1.2 * self.load, **kwargs)
        self.m = self.c.pool_size - self.c.pool_min_size
        self.c.wait_for_clean(timeout=40 * self._load())
        self.cl = self.c.client()
        # injection + scheduled scrub live from the start
        self._set_injection()
        try:
            self.cl.config_set("osd_scrub_interval", 3.0,
                                timeout=20 * self._load())
            self.cl.config_set("osd_scrub_auto_repair", "true",
                               timeout=20 * self._load())
        except TimeoutError as e:
            self._parked("config_set scrub", e)
        if self.disk_full and self.osd_procs:
            raise ValueError("disk_full needs in-process daemons "
                             "(capacity shrink + fault arming reach "
                             "stores through daemon RAM)")
        if self.link_degrade and self.osd_procs:
            raise ValueError("link_degrade needs in-process daemons "
                             "(delay injection + link trackers live "
                             "in daemon RAM)")
        if self.transient_fraction > 0:
            if self.osd_procs:
                raise ValueError("transient_fraction needs in-process "
                                 "daemons (policy counters live in "
                                 "daemon RAM)")
            try:
                self.cl.config_set("osd_repair_delay",
                                   self.repair_delay,
                                   timeout=20 * self._load())
            except TimeoutError as e:
                self._parked("config_set osd_repair_delay", e)
        return self

    def teardown(self) -> None:
        if self.c is None:
            return
        self.c.inject_socket_failures(0)
        self.c.inject_delays(0, 0.0)
        self.c.heal_link_degrades()
        self.c.shutdown()

    def _set_injection(self) -> None:
        every_sock = self.rng.randrange(8, 14)
        every_delay = self.rng.randrange(5, 10)
        max_ms = self.rng.uniform(4.0, 12.0)
        alive = sorted(set(self.c.osd_ids()) - self.dead_osds)
        self.c.inject_socket_failures(every_sock, osds=alive,
                                      seed=self.seed)
        self.c.inject_delays(every_delay, max_ms, osds=alive,
                             seed=self.seed)
        self._log(f"inject socket_failures={every_sock} "
                  f"delay=({every_delay}, {max_ms:.1f}ms)")

    # -- fault + IO actions --------------------------------------------------

    def act_write(self) -> None:
        objs = {n: self.rng.randbytes(self.rng.randrange(50, 900))
                for n in self._fresh_names(self.rng.randrange(2, 5))}
        try:
            self.cl.write(objs)
        except (ConnectionError, OSError, RuntimeError) as e:
            self.unknown.update(objs)
            self._parked("write", e)
            return
        self.shadow.update(objs)
        self.removed -= set(objs)
        self._log(f"write {len(objs)} objects")

    def act_overwrite(self) -> None:
        # target drawn from the DETERMINISTIC name counter, never from
        # the ack-dependent shadow: which ops got parked varies run to
        # run (thread timing), and a state-dependent candidate set
        # would desync the RNG stream between a run and its replay
        if not self._obj_i:
            return
        name = f"thrash-{self.seed}-{self.rng.randrange(self._obj_i)}"
        data = self.rng.randbytes(self.rng.randrange(50, 900))
        try:
            self.cl.write({name: data})
        except (ConnectionError, OSError, RuntimeError) as e:
            self.unknown.add(name)
            self._parked("overwrite", e)
            return
        self.shadow[name] = data
        self.removed.discard(name)
        self.unknown.discard(name)   # ack resolves an unknown fate
        self._log(f"overwrite {name}")

    def act_remove(self) -> None:
        if self._obj_i < 3:
            return
        name = f"thrash-{self.seed}-{self.rng.randrange(self._obj_i)}"
        try:
            self.cl.remove(name)     # idempotent: absent names ack too
        except (ConnectionError, OSError, RuntimeError, KeyError) as e:
            self.unknown.add(name)
            self._parked("remove", e)
            return
        self.shadow.pop(name, None)
        self.removed.add(name)
        self.unknown.discard(name)
        self._log(f"remove {name}")

    def act_kill_osd(self) -> None:
        # transient victims count against the concurrent-death budget
        # (data safety) but are DRAWN from their own stream — with
        # transient_fraction=0 this is bit-identical to the pre-r17
        # schedule
        alive = sorted(set(self.c.osd_ids()) - self.dead_osds
                       - set(self.transient_dead))
        if len(self.dead_osds) + len(self.transient_dead) >= self.m \
                or not alive:
            return
        victim = alive[self.rng.randrange(len(alive))]
        self.c.kill_osd(victim)
        self.dead_osds.add(victim)
        self._log(f"kill osd.{victim}")

    def act_revive_osd(self) -> None:
        if not self.dead_osds:
            return
        dead = sorted(self.dead_osds)
        victim = dead[self.rng.randrange(len(dead))]
        self.c.revive_osd(victim)
        self.dead_osds.discard(victim)
        # the revived daemon rejoins the injection matrix
        self.c.inject_socket_failures(self.rng.randrange(8, 14),
                                      osds=[victim], seed=self.seed)
        self.c.inject_delays(self.rng.randrange(5, 10),
                             self.rng.uniform(4.0, 12.0),
                             osds=[victim], seed=self.seed)
        self._log(f"revive osd.{victim}")

    def act_remount(self) -> None:
        """Kill + immediate revive: on TinStore this is a real WAL+
        checkpoint remount under traffic; on MemStore a process
        restart with state kept by fiat."""
        alive = sorted(set(self.c.osd_ids()) - self.dead_osds
                       - set(self.transient_dead))
        if len(self.dead_osds) + len(self.transient_dead) >= self.m \
                or not alive:
            return
        victim = alive[self.rng.randrange(len(alive))]
        self.c.kill_osd(victim)
        self.c.revive_osd(victim)
        self.c.inject_socket_failures(self.rng.randrange(8, 14),
                                      osds=[victim], seed=self.seed)
        self._log(f"remount osd.{victim}")

    def act_socket_failures(self) -> None:
        self._set_injection()

    def act_delays(self) -> None:
        self._set_injection()

    def act_mon_kill(self) -> None:
        # allowed to take out the MAJORITY: the quorum-loss map freeze
        # (and up_thru activation stall) is part of what chaos must
        # exercise; the round's heal revives them
        alive = sorted(set(range(3)) - self.dead_mons)
        if len(self.dead_mons) >= 2 or not alive:
            return
        victim = alive[self.rng.randrange(len(alive))]
        self.c.kill_mon(victim)
        self.dead_mons.add(victim)
        self._log(f"kill mon.{victim}")

    def act_mon_revive(self) -> None:
        if not self.dead_mons:
            return
        dead = sorted(self.dead_mons)
        victim = dead[self.rng.randrange(len(dead))]
        self.c.revive_mon(victim)
        self.dead_mons.discard(victim)
        self._log(f"revive mon.{victim}")

    def act_deep_scrub(self) -> None:
        ps = self.rng.randrange(self.pg_num)
        try:
            self.cl.deep_scrub(ps)
        except (ConnectionError, OSError, RuntimeError) as e:
            self._parked("deep_scrub", e)
            return
        # the report content is run-dependent (timing); the schedule
        # line must stay replay-identical
        self._log(f"deep_scrub pg 1.{ps}")

    # -- transient failures (r17) -------------------------------------------

    _QUIET_PREFIXES = ("inject", "parked", "transient")

    def _live_daemons(self):
        return [d for d in self.c.osds.values() if not d._stop.is_set()]

    def _repair_bytes(self) -> int:
        """Cluster-wide repair traffic counter: decode rebuilds +
        helper pulls + backfill copies (the storm bench's metric)."""
        return sum(d.ec_perf.get("recovered_bytes")
                   + d.ec_perf.get("recover_wire_bytes")
                   + d.perf.get("move_bytes")
                   for d in self._live_daemons())

    def _policy_counter(self, key: str) -> int:
        return sum(d.repair_policy.counters.get(key, 0)
                   for d in self._live_daemons())

    def _transient_sweep(self, round_i: int) -> None:
        """Seeded transient kills: each victim auto-revives at a drawn
        fraction of the repair delay — inside the window (the policy
        must cancel with zero moved bytes) or outside it (the window
        expires, the rebuild runs, the revive copies back: the eager
        cost lazy repair avoids for the inside draws). Draw VALUES
        come from trans_rng only; wall-clock execution (load) never
        feeds back into any RNG stream."""
        if self.transient_fraction <= 0:
            return
        n = self.trans_rng.randrange(1, 3)
        for _ in range(n):
            if self.trans_rng.random() >= self.transient_fraction:
                continue
            alive = sorted(set(self.c.osd_ids()) - self.dead_osds
                           - set(self.transient_dead))
            if (len(self.dead_osds) + len(self.transient_dead)
                    >= max(1, self.m - 1)) or not alive:
                # keep >= 1 spare redundancy so deferral (not the m-1
                # override) is what these kills exercise
                continue
            victim = alive[self.trans_rng.randrange(len(alive))]
            inside = self.trans_rng.random() < 0.7
            frac = self.trans_rng.uniform(0.35, 0.6) if inside \
                else self.trans_rng.uniform(1.3, 1.7)
            # quiet probe: half the inside draws BLOCK the schedule
            # until the revive deadline — a guaranteed quiet window,
            # so invariant (a)'s zero-byte check actually fires under
            # chaos instead of waiting for the menu to go silent. The
            # check needs a QUIET START too: background recovery
            # already in flight (an injection-suspected peer's
            # catch-up) would move bytes the victim never caused.
            probe = inside and self.trans_rng.random() < 0.5
            b0 = self._repair_bytes()
            base = (self._policy_counter("repair_urgent_overrides"),
                    self._policy_counter("repair_deferred_confirmed"))
            quiet_start = (not self.dead_osds and not self.dead_mons
                           and not self.transient_dead and all(
                               not d._recovering
                               and not d.repair_policy.parked
                               and not d.suspect
                               for d in self._live_daemons()))
            self.c.kill_osd(victim)
            deadline = time.monotonic() + frac * self.repair_delay
            self.transient_dead[victim] = (
                deadline, inside, quiet_start, len(self.schedule),
                b0, base)
            self.transient_kills += 1
            self._log(f"transient kill osd.{victim} "
                      f"({'inside' if inside else 'outside'} window, "
                      f"revive at {frac:.2f}x delay"
                      f"{', quiet probe' if probe else ''})")
            if probe:
                while time.monotonic() < deadline:
                    time.sleep(0.1)
                self._tick_transients()

    def _tick_transients(self, final: bool = False) -> None:
        """Revive due transient victims; `final` (the heal) waits out
        and revives everything still pending. An inside-window revive
        whose down-window was QUIET (no other fault or client
        mutation in the schedule since the kill) runs invariant (a):
        the policy must cancel the parked rebuild on a cursor
        re-check alone — ZERO repair bytes moved."""
        if not self.transient_dead:
            return
        now = time.monotonic()
        for victim in sorted(self.transient_dead):
            deadline, inside, quiet_start, kill_idx, b0, base = \
                self.transient_dead[victim]
            if not final and now < deadline:
                continue
            if final and now < deadline:
                # the heal waits the window out so outside-window
                # draws really see their deferral expire (bounded:
                # draws cap at 1.7x delay)
                time.sleep(min(max(0.0, deadline - now),
                               2.0 * self.repair_delay))
            del self.transient_dead[victim]
            quiet = quiet_start and all(
                line.startswith(self._QUIET_PREFIXES)
                for line in self.schedule[kill_idx + 1:])
            self.c.revive_osd(victim)
            if inside:
                self.transient_revives_inside += 1
            self._log(f"transient revive osd.{victim} "
                      f"({'inside' if inside else 'outside'} window, "
                      f"quiet={quiet})")
            if inside and quiet:
                self._check_inside_revive_noop(victim, b0, base)
            now = time.monotonic()

    def _check_inside_revive_noop(self, victim: int, b0: int,
                                  base: tuple) -> None:
        """Invariant (a): a within-window revive of a quiet PG set
        moves NO repair bytes — the cancel is a cursor/version
        re-check. Waits (load-scaled) for the cancel to land, then
        compares the cluster repair-bytes counter to the at-kill
        snapshot."""
        deadline = time.monotonic() + 10.0 * self._load()
        while time.monotonic() < deadline:
            parked = any(victim in ent["dead"]
                         for d in self._live_daemons()
                         for ent in d.repair_policy.parked.values())
            if not parked and all(
                    d.osdmap is not None and d.osdmap.osd_up[victim]
                    for d in self._live_daemons()):
                break
            time.sleep(0.1)
        time.sleep(0.3 * self._load())   # let an (illegal) rebuild
        b1 = self._repair_bytes()        # actually show up
        # a spurious down-mark of ANOTHER osd during the window (load
        # + injection stretching heartbeats) can legitimately move
        # bytes: a second loss fires the m-1 override, or an expired
        # window confirms. Those are the policy WORKING — skip the
        # zero-byte claim, don't fail it.
        overrides = (self._policy_counter("repair_urgent_overrides"),
                     self._policy_counter("repair_deferred_confirmed"))
        if overrides != base:
            self.transient_noop_skips += 1
            self._log(f"transient noop check osd.{victim}: skipped "
                      f"(concurrent override/confirm)")
            return
        if b1 != b0:
            self._violate(
                f"transient revive of osd.{victim} inside the repair "
                f"window moved {b1 - b0} repair bytes over a quiet "
                f"window (lazy repair must cancel with a cursor "
                f"re-check only)")
        self.transient_noop_checks += 1
        self._log(f"transient noop check osd.{victim}: 0 bytes ok")

    def _check_policy_invariants(self, round_i: int) -> None:
        """Invariant (b): no stripe waits at m-1 while the queue holds
        healthier stripes — structurally, the policy never PARKS an
        at-risk stripe (repair_urgent_parked == 0) and never ships a
        risk-inverted queue under risk order (repair_risk_inversions
        == 0). Asserted every heal, transient mode or not."""
        parked = self._policy_counter("repair_urgent_parked")
        if parked:
            self._violate(f"round {round_i}: {parked} at-m-1 "
                          f"stripe(s) were parked behind the repair "
                          f"delay")
        live = self._live_daemons()
        order = str(live[0].config["osd_repair_queue_order"]) \
            if live else "risk"
        inv = self._policy_counter("repair_risk_inversions")
        if inv and order == "risk":
            self._violate(f"round {round_i}: {inv} risk "
                          f"inversion(s) in the rebuild queue under "
                          f"risk order")

    # -- capacity exhaustion (r21) --------------------------------------------

    #: store txn phases the one-shot ENOSPC draw picks from (the
    #: store/KV `set_fault` hook points; mem has no WAL/flush plane)
    _ENOSPC_PHASES = {
        "mem": ("txn.apply",),
        "tin": ("txn.apply", "wal.append", "flush.segment-written",
                "flush.manifest-swapped", "compact.segments-written",
                "compact.manifest-swapped"),
    }

    def _enospc_sweep(self, round_i: int) -> None:
        """Arm ONE one-shot ENOSPC at a drawn (victim, txn phase) for
        this round's fault window. Whatever path trips it — a client
        write's apply, a replica subop, WAL append, a background
        flush/compact — must abort atomically: the op parks as
        unknown like any other mid-chaos failure, and the torn-store
        claim is settled by the heal's exactly-once reads plus the
        final offline fsck. Draws come from full_rng only."""
        if not self.disk_full:
            return
        victims = sorted(self.c.osd_ids())
        victim = victims[self.full_rng.randrange(len(victims))]
        phases = self._ENOSPC_PHASES[self.store]
        phase = phases[self.full_rng.randrange(len(phases))]
        armed = {"n": 1}

        def fault(point, _phase=phase, _armed=armed):
            if point == _phase and _armed["n"] > 0:
                _armed["n"] -= 1
                import errno
                raise OSError(errno.ENOSPC,
                              f"injected ENOSPC at {point}")

        self.c.osds[victim].store.set_fault(fault)
        self._armed_faults.append((victim, phase, armed))
        self.enospc_injected += 1
        self._log(f"round {round_i}: armed one-shot ENOSPC on "
                  f"osd.{victim} at {phase}")

    def _clear_faults(self) -> None:
        """Disarm every injected fault (heal entry: an unfired flush/
        compact fault must not land mid-recovery-writeback AFTER the
        window it belonged to) and tally what actually fired."""
        if not self._armed_faults:
            return
        for _victim, _phase, armed in self._armed_faults:
            self.enospc_fired += 1 - armed["n"]
        self._armed_faults.clear()
        for d in self._live_daemons():
            d.store.set_fault(None)

    def _disk_full_window(self, round_i: int) -> None:
        """One capacity-exhaustion window against a CLEAN cluster
        (post-heal): shrink every store with data to just over the
        failsafe ratio, wait for the mon ladder to commit FULL, and
        assert the RADOS full contract under live injection:

          * a background writer PARKS — zero op_errors surface;
          * every acked object still READS bit-exact mid-FULL;
          * after capacity restore the flag clears and every parked
            write drains EXACTLY-ONCE (bytes verified by read-back
            here and again by the next heal's sweep).

        Draw values come from full_rng; deadlines are load-scaled
        wall clock that never feeds back into any RNG stream."""
        if not self.disk_full:
            return
        import threading
        names = self._fresh_names(self.full_rng.randrange(3, 6))
        objs = {n: self.full_rng.randbytes(
                    self.full_rng.randrange(100, 600))
                for n in names}
        shrunk: list[int] = []
        empty: list[int] = []
        cl2 = self.c.client()
        acked: dict[str, bytes] = {}
        errors: list[str] = []

        def _writer():
            for n_, data in objs.items():
                try:
                    cl2.write({n_: data})
                except Exception as e:   # noqa: BLE001 — ANY error
                    errors.append(       # here violates the contract
                        f"{n_}: {type(e).__name__}: {e}")
                    return
                acked[n_] = data

        t = threading.Thread(target=_writer, daemon=True)
        try:
            for o in sorted(self.c.osd_ids()):
                st = self.c.osds[o].store.statfs()
                used = int(st.get("used", 0))
                if used <= 0:
                    empty.append(o)   # no ratio to push over: leave
                    continue          # unbounded (can't ENOSPC either)
                # used/total ~ 0.98: over failsafe (0.97) AND over
                # mon_osd_full_ratio (0.95) in one move
                self.c.osds[o].store.set_capacity(
                    max(1, int(used / 0.98)))
                shrunk.append(o)
            if not shrunk:
                self._log(f"round {round_i}: disk_full window skipped "
                          f"(no store holds data yet)")
                return
            self._log(f"round {round_i}: disk_full window — shrank "
                      f"{len(shrunk)} store(s) over failsafe"
                      + (f" ({len(empty)} empty left unbounded)"
                         if empty else ""))
            if not empty:
                # every primary is gated: the first write bounces at
                # the OSD failsafe (statfs-only, pre-map) and the
                # client parks on the pinned epoch — start now so the
                # hard-stop path gets chaos coverage too
                t.start()
            if not self._poll_df(True, 30.0 * self._load()):
                self._violate(
                    f"round {round_i}: mon ladder never committed "
                    f"cluster FULL ({len(shrunk)} stores over the "
                    f"full ratio)")
            if not t.is_alive():
                # an empty-store primary could have raced writes
                # through pre-flip: start (or observe) post-flip
                if empty and not acked and not errors:
                    t.start()
            self.full_windows += 1
            # reads must keep serving while writes are parked
            for name in sorted(set(self.shadow) - self.unknown):
                try:
                    got = self.cl.read(name)
                except Exception as e:   # noqa: BLE001
                    self._violate(
                        f"round {round_i}: read of acked {name!r} "
                        f"failed under cluster FULL "
                        f"({type(e).__name__}: {e}) — reads must not "
                        f"park behind the full ladder")
                if got != self.shadow[name]:
                    self._violate(
                        f"round {round_i}: read of {name!r} under "
                        f"cluster FULL diverged from last acked bytes")
                self.full_reads_served += 1
            # the writer must be PARKED, not errored: backoff counter
            # growing and no op_errors surfaced
            full_wait = 30.0 * self._load()
            deadline = time.monotonic() + full_wait
            parked = False
            while time.monotonic() < deadline:
                if errors:
                    break
                fb = cl2.perf.dump().get("full_backoff_time") or {}
                if int(fb.get("avgcount", 0)) > 0:
                    parked = True
                    break
                time.sleep(0.2)
            if errors:
                self._violate(
                    f"round {round_i}: op_error surfaced to a writer "
                    f"under cluster FULL (must park, never error): "
                    f"{errors[0]}")
            if not parked:
                self._violate(
                    f"round {round_i}: writer neither parked nor "
                    f"errored under cluster FULL within "
                    f"{full_wait:.0f}s")
        finally:
            for o in shrunk:
                self.c.osds[o].store.set_capacity(
                    self.c.store_capacity)
        if not self._poll_df(False, 30.0 * self._load()):
            self._violate(f"round {round_i}: cluster FULL flag never "
                          f"cleared after capacity restore")
        drain_wait = 60.0 * self._load()
        t.join(drain_wait)
        if t.is_alive():
            self._violate(
                f"round {round_i}: parked writes failed to drain "
                f"within {drain_wait:.0f}s of the FULL flag "
                f"clearing")
        if errors:
            self._violate(
                f"round {round_i}: op_error surfaced draining parked "
                f"writes: {errors[0]}")
        if len(acked) != len(objs):
            self._violate(
                f"round {round_i}: only {len(acked)}/{len(objs)} "
                f"parked writes drained after restore")
        # exactly-once: every drained write reads back bit-exact NOW
        # (and again at the next heal via the shadow oracle)
        for n_, data in sorted(acked.items()):
            try:
                got = self.cl.read(n_)
            except Exception as e:   # noqa: BLE001
                self._violate(f"round {round_i}: drained write "
                              f"{n_!r} unreadable ({e})")
            if got != data:
                self._violate(f"round {round_i}: drained write "
                              f"{n_!r} bytes diverged")
        self.shadow.update(acked)
        self.removed -= set(acked)
        self.full_parked_drained += len(acked)
        self._log(f"round {round_i}: disk_full window ok — "
                  f"{len(acked)} parked writes drained exactly-once, "
                  f"{self.full_reads_served} reads served under FULL")

    def _poll_df(self, want_full: bool, deadline_s: float) -> dict:
        """Poll the mon `df` command until its committed-map FULL flag
        matches; {} on deadline (the caller decides the violation)."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                df = self.cl.mon_command("df",
                                         timeout=10.0 * self._load())
            except Exception:   # noqa: BLE001 — mon hunt mid-chaos
                df = None
            if isinstance(df, dict) \
                    and bool(df.get("cluster_full")) == want_full:
                return df
            time.sleep(0.2)
        return {}

    # -- network degrade (r22) ------------------------------------------------

    def _link_degrade_window(self, round_i: int) -> None:
        """One directed-link degrade window against a CLEAN cluster
        (post-heal): inject a drawn one-way delay on exactly one
        sender->peer edge and hold the netobs plane to its contract:

          * OSD_SLOW_PING_TIME flips within two heartbeat grace
            windows (plus the MgrReport pipe), naming EXACTLY the
            degraded link and no other;
          * the sender's helper-cost feed reprices the degraded peer
            worst among live helpers, pinned on the
            net_helper_penalties counter (the planner input r14/r11
            rank by — routing around the link IS this repricing);
          * after heal the check clears within the same budget.

        Draw values come from link_rng only; deadlines are load-scaled
        wall clock that never feeds back into any RNG stream."""
        if not self.link_degrade:
            return
        live = sorted(set(self.c.osd_ids()) - self.dead_osds)
        if len(live) < 3:
            self._log(f"round {round_i}: link_degrade window skipped "
                      f"(<3 live osds)")
            return
        a = live[self.link_rng.randrange(len(live))]
        others = [o for o in live if o != a]
        b = others[self.link_rng.randrange(len(others))]
        delay_ms = self.link_rng.uniform(250.0, 400.0)
        jitter_ms = self.link_rng.uniform(0.0, 30.0)
        thr_ms = 100.0   # 10-50x an in-proc RTT, 1/3 of the delay
        try:
            self.cl.config_set("mon_warn_on_slow_ping_time", thr_ms,
                               timeout=20 * self._load())
        except TimeoutError as e:
            self._parked("config_set mon_warn_on_slow_ping_time", e)
            return
        d = self.c.osds[a]
        pen0 = d.perf.get("net_helper_penalties")
        grace = float(d.config["osd_heartbeat_grace"])
        report_s = float(d.config["mgr_report_interval"])
        budget = 2.0 * grace + 2.0 * report_s + 2.0 * self._load()
        # settle: the kill/revive phase just before this window leaves
        # REAL slow residue in the matrix (pings to a dead peer are
        # answered late on its revive), and the exact-link contract
        # only holds against a quiet baseline — wait for any residue
        # to decay below the threshold before injecting
        settle = budget + 4.0 * self._load()
        deadline = time.monotonic() + settle
        while self._poll_slow_ping(0.0) is not None:
            if time.monotonic() >= deadline:
                self._log(f"round {round_i}: link_degrade window "
                          f"skipped — pre-existing slow links never "
                          f"settled in {settle:.1f}s")
                try:
                    self.cl.config_set("mon_warn_on_slow_ping_time",
                                       0.0, timeout=20 * self._load())
                except TimeoutError as e:
                    self._parked(
                        "config_set mon_warn_on_slow_ping_time", e)
                return
            time.sleep(0.3)
        self.c.link_degrade(a, b, delay_ms, jitter_ms, seed=self.seed)
        self.link_windows += 1
        self._log(f"round {round_i}: link_degrade window — "
                  f"osd.{a} -> osd.{b} +{delay_ms:.0f}ms "
                  f"(jitter {jitter_ms:.0f}ms, threshold {thr_ms:.0f}ms)")
        want = f"osd.{a} -> osd.{b} (hb)"
        try:
            fired = self._poll_slow_ping(budget)
            if fired is None:
                self._violate(
                    f"round {round_i}: OSD_SLOW_PING_TIME never fired "
                    f"within {budget:.1f}s of degrading "
                    f"osd.{a} -> osd.{b} by {delay_ms:.0f}ms")
            if not any(want in ln for ln in fired["detail"]):
                self._violate(
                    f"round {round_i}: OSD_SLOW_PING_TIME fired but "
                    f"named {fired['detail']!r}, not the degraded "
                    f"link {want!r}")
            strays = [ln for ln in fired["detail"] if want not in ln]
            if strays:
                self._violate(
                    f"round {round_i}: OSD_SLOW_PING_TIME named "
                    f"links beyond the degraded one: {strays!r}")
            self.link_health_flips += 1
            # the feed must shift helper selection: the sender now
            # prices b worst among live helpers, and the blend took
            # the hb-EWMA branch (counter-pinned)
            from types import SimpleNamespace
            costs = d._helper_costs(SimpleNamespace(acting=live))
            ranked = sorted((s for s, o in enumerate(live) if o != a),
                            key=lambda s: costs[s])
            if live[ranked[-1]] != b:
                self._violate(
                    f"round {round_i}: degraded helper osd.{b} not "
                    f"priced worst by osd.{a}'s feed "
                    f"(costs {dict(zip(live, (costs[s] for s in range(len(live)))))!r})")
            pen1 = d.perf.get("net_helper_penalties")
            if pen1 <= pen0:
                self._violate(
                    f"round {round_i}: net_helper_penalties never "
                    f"moved ({pen0} -> {pen1}) — the hb-RTT feed did "
                    f"not join the helper-cost blend")
            self.link_repriced += 1
            self._log(f"round {round_i}: link_degrade flip ok — "
                      f"named {want!r}, osd.{b} priced "
                      f"{costs[ranked[-1]]}us (next worst "
                      f"{costs[ranked[-2]]}us)")
        finally:
            self.c.heal_link_degrades()
        # clear: the ewma halves per undelayed ping (alpha 0.5), so a
        # couple of sweeps bring it under the threshold; budget the
        # same pipe slack plus a few extra pings
        clear_budget = budget + 4.0 * self._load()
        deadline = time.monotonic() + clear_budget
        cleared = False
        while time.monotonic() < deadline:
            if self._poll_slow_ping(0.0) is None:
                cleared = True
                break
            time.sleep(0.3)
        if not cleared:
            self._violate(
                f"round {round_i}: OSD_SLOW_PING_TIME failed to "
                f"clear within {clear_budget:.1f}s of healing "
                f"osd.{a} -> osd.{b}")
        self.link_health_clears += 1
        try:
            self.cl.config_set("mon_warn_on_slow_ping_time", 0.0,
                               timeout=20 * self._load())
        except TimeoutError as e:
            self._parked("config_set mon_warn_on_slow_ping_time", e)
        self._log(f"round {round_i}: link_degrade window ok — "
                  f"health cleared after heal")

    def _poll_slow_ping(self, budget_s: float) -> dict | None:
        """Poll `health detail` up to budget_s for OSD_SLOW_PING_TIME;
        the check dict if present, None if absent at deadline (a
        budget of 0 means one immediate look)."""
        deadline = time.monotonic() + budget_s
        while True:
            try:
                h = self.cl.health(detail=True)
            except Exception:   # noqa: BLE001 — mon hunt mid-chaos
                h = None
            if h is not None:
                fired = next((ck for ck in h.get("checks", [])
                              if ck["code"] == "OSD_SLOW_PING_TIME"),
                             None)
                if fired is not None:
                    return fired
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.2)

    # -- the schedule --------------------------------------------------------

    def _menu(self):
        acts = []
        for name, (weight, _desc) in KNOBS.items():
            acts.extend([getattr(self, f"act_{name}")] * weight)
        return acts

    def run(self) -> dict:
        """Execute rounds of (faults under I/O, heal, invariants).
        Returns the report dict; raises InvariantViolation (with the
        seed + reproducer in the message) on any violated invariant."""
        t0 = time.monotonic()
        if self.c is None:
            self.setup()
        try:
            menu = self._menu()
            for round_i in range(self.rounds):
                self.act_write()     # every round has data on the line
                self._transient_sweep(round_i)
                self._enospc_sweep(round_i)
                for _ in range(self.ops):
                    menu[self.rng.randrange(len(menu))]()
                    time.sleep(0.15)
                    self._tick_transients()
                if self.overwrite_during_faults:
                    self._overwrite_sweep_during_faults(round_i)
                if self.workload_profile:
                    self._workload_sweep_during_faults(round_i)
                if self.read_during_faults:
                    self._read_sweep_during_faults(round_i)
                self._heal_and_check(round_i)
                # r21: the capacity-exhaustion window runs against the
                # healed (clean) cluster so the only thing parking the
                # writer is the full ladder itself
                self._disk_full_window(round_i)
                # r22: likewise post-heal — the only slow link must be
                # the injected one, or exact-link naming can't hold
                self._link_degrade_window(round_i)
            report = self._final_report(time.monotonic() - t0)
        finally:
            self.teardown()
        if self.store == "tin":
            self._check_fsck(report)
        self._log(f"OK: {report['objects_verified']} objects verified "
                  f"across {self.rounds} rounds")
        return report

    # -- heal + invariants ---------------------------------------------------

    def _read_sweep_during_faults(self, round_i: int) -> None:
        """Invariant: DEGRADED READS NEVER BLOCK — with the round's
        faults still live (dead OSDs un-revived, dead monitors
        un-revived, injection running), every acked object must read
        back bit-exact through the degraded-read fast path. No heal,
        no wait_for_clean first: a read that can only succeed after
        convergence is exactly the tail this invariant forbids."""
        names = sorted(set(self.shadow) - self.unknown)
        for name in names:
            try:
                got = self.cl.read(name)
            except Exception as e:   # noqa: BLE001 — any failure here
                self._violate(       # means the read blocked on heal
                    f"round {round_i}: degraded read of acked "
                    f"{name!r} failed mid-faults ({type(e).__name__}: "
                    f"{e}) — reads must not wait for wait_for_clean")
            if got != self.shadow[name]:
                self._violate(f"round {round_i}: degraded read of "
                              f"{name!r} diverged from last acked "
                              f"bytes")
            self.degraded_read_checks += 1
        self._log(f"round {round_i}: degraded-read sweep ok "
                  f"({len(names)} objects, faults live)")

    def _overwrite_sweep_during_faults(self, round_i: int) -> None:
        """r16 invariant input: partial overwrites (write_at) WITH the
        round's faults still live — dead OSDs un-revived, injection
        running — so kills land mid-RMW and the stripe journal's
        replay has to hold the exactly-once/no-resurrection line.
        Draws come from the dedicated rmw stream and never read
        ack-dependent state, so a seed replays the identical sweep."""
        n = self.rmw_rng.randrange(2, 5)
        for _ in range(n):
            if not self._obj_i:
                return
            name = f"thrash-{self.seed}-" \
                   f"{self.rmw_rng.randrange(self._obj_i)}"
            off = self.rmw_rng.randrange(0, 700)
            patch = self.rmw_rng.randbytes(
                self.rmw_rng.randrange(8, 200))
            try:
                self.cl.write_at(name, off, patch)
            except (ConnectionError, OSError, RuntimeError,
                    KeyError) as e:
                self.unknown.add(name)
                self._parked(f"write_at {name}", e)
                continue
            if name in self.unknown:
                # base bytes unknowable: a patch over them proves
                # nothing either way — the object stays unclaimed
                continue
            old = self.shadow.get(name, b"")
            buf = bytearray(max(len(old), off + len(patch)))
            buf[:len(old)] = old
            buf[off:off + len(patch)] = patch
            self.shadow[name] = bytes(buf)
            self.removed.discard(name)
            self.rmw_overwrite_checks += 1
            self._log(f"round {round_i}: write_at {name} "
                      f"[{off},{off + len(patch)})")

    def _workload_sweep_during_faults(self, round_i: int) -> None:
        """r20 invariant input: a tenant-profile traffic burst WITH
        the round's faults still live — the workload engine's seeded
        stream generator drives reads, write_at patches, appends and
        full rewrites against thrash-owned objects, so fault windows
        see realistic mixed traffic, not just the menu's writes.
        Streams come from (profile, seed ^ round) alone — the
        dedicated-stream discipline: a seed replays the identical
        burst, and cells without --workload-profile are untouched."""
        from ..workload import OpStream
        from ..workload.profiles import BUILTIN_PROFILES, TenantProfile
        from ..workload.streams import payload_for
        spec = BUILTIN_PROFILES.get(self.workload_profile)
        if spec is None:
            import json as _json
            spec = _json.loads(self.workload_profile)
        p = TenantProfile.from_dict(spec)
        seed = self.seed ^ 0x301D ^ round_i
        # ~0.5 s of the profile's schedule, executed back-to-back (a
        # sweep, not a paced run); payload slices are seed-derived too
        ops = OpStream(p, seed).generate(0.5)
        payload = payload_for(p, seed)
        for op in ops:
            name = f"wl-{self.seed}-{p.name}-{op.obj}"
            try:
                if op.kind == "read":
                    if name not in self.shadow \
                            or name in self.unknown:
                        continue
                    got = self.cl.read(name)
                    if got != self.shadow[name]:
                        self._violate(
                            f"round {round_i}: workload read of "
                            f"{name!r} diverged from last acked "
                            f"bytes")
                elif op.kind == "write_at":
                    patch = payload[:op.size]
                    self.cl.write_at(name, op.offset, patch)
                    if name not in self.unknown:
                        old = self.shadow.get(name, b"")
                        buf = bytearray(max(len(old),
                                            op.offset + len(patch)))
                        buf[:len(old)] = old
                        buf[op.offset:op.offset + len(patch)] = patch
                        self.shadow[name] = bytes(buf)
                        self.removed.discard(name)
                elif op.kind == "append":
                    data = payload[:op.size]
                    self.cl.append(name, data)
                    if name not in self.unknown:
                        self.shadow[name] = \
                            self.shadow.get(name, b"") + data
                        self.removed.discard(name)
                else:       # write_full
                    data = payload[:p.object_size]
                    self.cl.write({name: data})
                    self.shadow[name] = data
                    self.removed.discard(name)
                    self.unknown.discard(name)
            except (ConnectionError, OSError, RuntimeError,
                    KeyError) as e:
                if op.kind != "read":
                    self.unknown.add(name)
                self._parked(f"workload {op.kind} {name}", e)
                continue
            self.workload_ops += 1
        self._log(f"round {round_i}: workload sweep "
                  f"[{p.name}] {self.workload_ops} ops total")

    def _heal_and_check(self, round_i: int) -> None:
        # r21: disarm any unfired ENOSPC faults first — heal-time
        # recovery writeback must not trip a fault that belonged to
        # the closed window
        self._clear_faults()
        # transient victims first: the heal waits their windows out so
        # outside-window draws exercise the expire->rebuild path
        self._tick_transients(final=True)
        for r in sorted(self.dead_mons):
            self.c.revive_mon(r)
        self.dead_mons.clear()
        for o in sorted(self.dead_osds):
            self.c.revive_osd(o)
        self.dead_osds.clear()
        if self.rotate_secrets:
            # deterministic per-round rotation (r15): every live
            # daemon — --osd-procs children via the control-pipe push
            # — refreshes its verifier; I/O must keep flowing through
            # the keep-window and clients re-fetch past it
            self.c.rotate_service_secrets("osd")
            self._log(f"round {round_i}: rotated osd service secrets")
        self._log(f"round {round_i}: healed; checking invariants")
        # invariant: CONVERGENCE — recovery + activation (up_thru)
        # must settle with injection still live (deadline scaled by
        # the host's load, not loosened: see load_factor)
        try:
            self.c.wait_for_clean(timeout=90 * self._load())
        except TimeoutError as e:
            self._violate(f"round {round_i}: cluster did not "
                          f"converge after heal ({e})")
        # invariant: EXACTLY-ONCE BYTES — every acked write reads back
        # the last acked value, byte-exact, through live injection
        for name in sorted(set(self.shadow) - self.unknown):
            try:
                got = self.cl.read(name)
            except Exception as e:   # noqa: BLE001 — any read failure
                self._violate(f"round {round_i}: acked object "
                              f"{name!r} unreadable ({e})")
            if got != self.shadow[name]:
                self._violate(f"round {round_i}: {name!r} bytes "
                              f"diverged from last acked write")
        # invariant: NO RESURRECTION — an acked remove stays removed
        # even after dead shards rejoined with stale copies
        for name in sorted(self.removed - self.unknown):
            try:
                self.cl.read(name)
            except KeyError:
                continue             # correctly gone
            except Exception as e:   # noqa: BLE001 — must be ENOENT,
                self._violate(       # not a transport wedge
                    f"round {round_i}: removed {name!r} read "
                    f"errored oddly ({e})")
            self._violate(f"round {round_i}: removed object "
                          f"{name!r} resurrected")
        # r17 policy invariants hold after every heal (transient mode
        # or not; counters are 0 when the policy never engaged)
        if not self.osd_procs:
            self._check_policy_invariants(round_i)

    def _final_report(self, elapsed: float) -> dict:
        return {
            "seed": self.seed,
            "store": self.store,
            "rounds": self.rounds,
            "objects_verified": len(set(self.shadow) - self.unknown),
            "removes_verified": len(self.removed - self.unknown),
            "unknown_fate": len(self.unknown),
            "degraded_read_checks": self.degraded_read_checks,
            "rmw_overwrite_checks": self.rmw_overwrite_checks,
            "workload_ops": self.workload_ops,
            "transient_kills": self.transient_kills,
            "transient_revives_inside": self.transient_revives_inside,
            "transient_noop_checks": self.transient_noop_checks,
            "transient_noop_skips": self.transient_noop_skips,
            "full_windows": self.full_windows,
            "full_reads_served": self.full_reads_served,
            "full_parked_drained": self.full_parked_drained,
            "enospc_injected": self.enospc_injected,
            "enospc_fired": self.enospc_fired,
            "link_windows": self.link_windows,
            "link_health_flips": self.link_health_flips,
            "link_health_clears": self.link_health_clears,
            "link_repriced": self.link_repriced,
            "writes_rejected_full":
                sum(d.perf.get("writes_rejected_full")
                    for d in self._live_daemons())
                if self.c is not None and not self.osd_procs else 0,
            "repair_deferred_stripes":
                self._policy_counter("repair_deferred_stripes")
                if self.c is not None and not self.osd_procs else 0,
            "repair_deferred_cancelled":
                self._policy_counter("repair_deferred_cancelled")
                if self.c is not None and not self.osd_procs else 0,
            "schedule_len": len(self.schedule),
            "elapsed_s": round(elapsed, 2),
            "repro": self.repro,
        }

    def _check_fsck(self, report: dict) -> None:
        """Invariant: FSCK-CLEAN REMOUNT — after the final shutdown
        (a crash, not a clean umount) every TinStore directory must
        audit clean offline. Orphan segments are crash artifacts the
        next mount reclaims, not corruption."""
        import os

        from ..osd.tinstore import TinStore
        checked = 0
        for osd in range(self.n_osds):
            path = os.path.join(self.c.store_dir, f"osd.{osd}")
            if not os.path.isdir(path):
                continue
            rep = TinStore.fsck(path)
            bad = (rep["errors"] or rep["extent_errors"]
                   or rep["bad_objects"])
            if bad:
                self._violate(f"fsck of {path} not clean: {bad}")
            checked += 1
        if not checked:
            self._violate("store=tin but no TinStore directories "
                          "found to fsck")
        report["fsck_clean_stores"] = checked
