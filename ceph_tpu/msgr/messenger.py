"""Messenger — typed, CRC-protected, lossless-peer RPC.

Rebuild of the reference's wire layer (ref: src/msg/Messenger.h
Messenger/Connection/Dispatcher; src/msg/async/AsyncMessenger.cc —
listen + per-connection state machines; src/msg/async/ProtocolV2.cc —
banner exchange, crc-protected frame segments, and RESET/reconnect
semantics; src/messages/*.h — typed Message subclasses). The control
plane the sim runs in-process gets a real cross-process transport
here: the native EC shim already crosses processes for DATA (its unix
socket), this module is the typed CONTROL path (the role MOSDPing /
MOSDPGLog / mon messages play).

Scope and mapping (SURVEY §2.5/§5): bulk data movement between chips
is ICI/DCN collectives, NOT this messenger — so this layer stays small
and correctness-first. Implemented faithfully:

* banner + identity handshake carrying the receiver's last-seen
  sequence number per peer, so a reconnect resumes exactly where the
  stream broke (the lossless_peer policy's replay);
* frames `[u32 len][u64 seq][u16 type][payload][u32 crc32c]` — the
  crc covers everything before it; a corrupt frame kills the
  connection (ProtocolV2 crc mode behavior), and the sender's replay
  queue redelivers on reconnect;
* explicit ACKs retire the sender's unacked queue; receivers dedup by
  (peer, seq) so redelivery is exactly-once upward;
* a Dispatcher callback per message type (ms_fast_dispatch role).

Threading model: one reader thread per connection + locked writers
(the reference runs epoll worker threads; blocking threads keep this
deterministic and dependency-free).
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque

from ..csum.reference import ceph_crc32c
from ..utils.encoding import Decoder, Encoder

BANNER = b"ceph_tpu msgr v2\n"
ACK_TYPE = 0

_MSG_TYPES: dict[int, type] = {}


def register_message(cls):
    """Class decorator: register a Message subclass by its type_id."""
    tid = cls.type_id
    if tid in _MSG_TYPES and _MSG_TYPES[tid] is not cls:
        raise ValueError(f"message type {tid} already registered")
    if tid == ACK_TYPE:
        raise ValueError("type 0 is reserved for ACK")
    _MSG_TYPES[tid] = cls
    return cls


class Message:
    """Typed payload (the Message subclass contract): subclasses set
    type_id and implement encode_payload/decode_payload."""

    type_id: int = -1

    def encode_payload(self, e: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def decode_payload(cls, d: Decoder) -> "Message":
        raise NotImplementedError


def _crc(data: bytes) -> int:
    return int(ceph_crc32c(0xFFFFFFFF, data)) & 0xFFFFFFFF


class _Conn:
    """One live socket + replay state toward one peer."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.wlock = threading.Lock()
        self.alive = True

    def send_frame(self, seq: int, type_id: int, payload: bytes) -> None:
        body = struct.pack("<QH", seq, type_id) + payload
        frame = struct.pack("<I", len(body)) + body
        frame += struct.pack("<I", _crc(frame))
        with self.wlock:
            self.sock.sendall(frame)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class Messenger:
    """Bind, connect, send typed messages, dispatch callbacks.

    Lossless-peer semantics: every logical message gets a sequence
    number; unacked messages survive connection death and are replayed
    after the automatic reconnect (send() never silently drops)."""

    def __init__(self, name: str, host: str = "127.0.0.1"):
        self.name = name
        self._handlers: dict[int, callable] = {}
        self._lock = threading.Lock()
        # one lock per PEER held across seq-assignment + transmit:
        # frames must hit the socket in sequence order or the
        # receiver's max-seq dedup would discard reordered messages,
        # and concurrent connects would race adopting sockets
        self._peer_locks: dict[str, threading.RLock] = {}
        # per-peer-name state (the lossless session, not the socket):
        self._out_seq: dict[str, int] = {}
        self._unacked: dict[str, deque] = {}   # (seq, type, payload)
        self._in_seq: dict[str, int] = {}      # last delivered seq
        self._conns: dict[str, _Conn] = {}
        self._addr_of: dict[str, tuple] = {}
        self._stopping = False
        self._listener = socket.create_server((host, 0))
        self.addr = self._listener.getsockname()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- dispatch ------------------------------------------------------------

    def register_handler(self, type_id: int, fn) -> None:
        """fn(peer_name: str, msg: Message) — ms_fast_dispatch."""
        self._handlers[type_id] = fn

    # -- connection management ----------------------------------------------

    def _accept_loop(self) -> None:
        import time
        while not self._stopping:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._stopping:
                    return
                # transient failure (e.g. EMFILE): a dead listener
                # would look exactly like a partition to peers — keep
                # accepting rather than silently going deaf
                time.sleep(0.05)
                continue
            threading.Thread(target=self._handshake_in, args=(sock,),
                             daemon=True).start()

    def _handshake_in(self, sock: socket.socket) -> None:
        try:
            if self._recv_exact(sock, len(BANNER)) != BANNER:
                sock.close()
                return
            nlen = struct.unpack("<H", self._recv_exact(sock, 2))[0]
            peer = self._recv_exact(sock, nlen).decode()
            # symmetric handshake: both sides exchange their last-seen
            # sequence so BOTH replay their unacked queues — an
            # acceptor has stranded messages too after a reconnect
            (peer_seen,) = struct.unpack(
                "<Q", self._recv_exact(sock, 8))
            sock.sendall(BANNER)
            with self._lock:
                last_seen = self._in_seq.get(peer, 0)
            sock.sendall(struct.pack("<Q", last_seen))
        except (OSError, ConnectionError, UnicodeDecodeError):
            sock.close()
            return
        conn = _Conn(sock)
        # adopt+replay must be one atomic step under the peer lock:
        # published-but-not-yet-replayed is a window where a concurrent
        # send() (which holds only the peer lock) could emit a NEW
        # higher-seq frame first, making the receiver's max-seq dedup
        # discard the later-replayed older frames — silent loss.
        # _connect() already orders it this way; mirror it here.
        with self._plock(peer):
            if not self._adopt(peer, conn, inbound=True):
                return
            self._replay(peer, conn, peer_seen)

    def _replay(self, peer: str, conn: _Conn, peer_seen: int) -> None:
        """Retire entries the peer's handshake already acknowledges
        (a lost final ACK must not wedge flush forever), then resend
        the rest in order (lossless_peer replay)."""
        with self._plock(peer):
            with self._lock:
                q = self._unacked.get(peer)
                while q and q[0][0] <= peer_seen:
                    q.popleft()
                pending = list(q or ())
            try:
                for seq, tid, payload in pending:
                    conn.send_frame(seq, tid, payload)
            except (OSError, ConnectionError):
                pass  # conn died again; next reconnect replays

    def _connect(self, peer: str) -> _Conn:
        """Dial + handshake + replay. Callers hold the peer lock, so
        only one connect per peer runs and replay order is exact."""
        with self._plock(peer):
            conn = self._conns.get(peer)
            if conn is not None and conn.alive:
                return conn  # someone beat us to it
            addr = self._addr_of[peer]
            sock = socket.create_connection(tuple(addr), timeout=10)
            sock.sendall(BANNER)
            name_b = self.name.encode()
            sock.sendall(struct.pack("<H", len(name_b)) + name_b)
            with self._lock:
                my_seen = self._in_seq.get(peer, 0)
            sock.sendall(struct.pack("<Q", my_seen))
            if self._recv_exact(sock, len(BANNER)) != BANNER:
                sock.close()
                raise ConnectionError(f"bad banner from {peer}")
            peer_seen = struct.unpack("<Q",
                                      self._recv_exact(sock, 8))[0]
            conn = _Conn(sock)
            if not self._adopt(peer, conn, inbound=False):
                raise ConnectionError(f"lost connection race to {peer}")
            self._replay(peer, conn, peer_seen)
            return conn

    def _adopt(self, peer: str, conn: _Conn, inbound: bool) -> bool:
        """Install the connection for `peer`, resolving simultaneous-
        connect races deterministically (ProtocolV2's race-winner
        rule): the LOWER name is the designated dialer, so when crossed
        dials collide, its outgoing socket wins and the other side's
        inbound attempt is refused. Returns False if refused."""
        with self._lock:
            old = self._conns.get(peer)
            if (inbound and self.name < peer
                    and old is not None and old.alive):
                keep_old = True
            else:
                keep_old = False
                self._conns[peer] = conn
        if keep_old:
            conn.close()
            return False
        if old is not None and old is not conn:
            old.close()
        threading.Thread(target=self._read_loop, args=(peer, conn),
                         daemon=True).start()
        return True

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = sock.recv(n - len(buf))
            if not got:
                raise ConnectionError("peer closed")
            buf += got
        return buf

    # -- send ----------------------------------------------------------------

    def add_peer(self, peer: str, addr) -> None:
        self._addr_of[peer] = tuple(addr)

    def _plock(self, peer: str) -> threading.RLock:
        with self._lock:
            lk = self._peer_locks.get(peer)
            if lk is None:
                lk = self._peer_locks[peer] = threading.RLock()
            return lk

    def send(self, peer: str, msg: Message) -> None:
        """Queue + transmit; survives connection death (replayed on
        the next reconnect). Raises only if the peer is unknown or the
        payload won't encode."""
        e = Encoder()
        msg.encode_payload(e)
        payload = e.bytes()
        with self._plock(peer):
            with self._lock:
                seq = self._out_seq.get(peer, 0) + 1
                self._out_seq[peer] = seq
                self._unacked.setdefault(peer, deque()).append(
                    (seq, msg.type_id, payload))
                conn = self._conns.get(peer)
            try:
                if conn is None or not conn.alive:
                    conn = self._connect(peer)
                    # _connect replayed the queue incl. this message
                    return
                conn.send_frame(seq, msg.type_id, payload)
            except (OSError, ConnectionError):
                # connection died mid-send: the message stays unacked
                # and replays on the next send/reconnect. Identity
                # check: a fresh conn adopted meanwhile must survive.
                with self._lock:
                    if conn is not None \
                            and self._conns.get(peer) is conn:
                        del self._conns[peer]

    def flush(self, peer: str, timeout: float = 10.0) -> bool:
        """Block until the peer acked everything (or timeout). The
        sender-side barrier tests use; returns False on timeout."""
        import time
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            with self._lock:
                if not self._unacked.get(peer):
                    return True
                conn = self._conns.get(peer)
            if conn is None or not conn.alive:
                try:
                    self._connect(peer)
                except (OSError, ConnectionError, KeyError):
                    pass
            time.sleep(0.01)
        return False

    # -- receive -------------------------------------------------------------

    def _read_loop(self, peer: str, conn: _Conn) -> None:
        try:
            while conn.alive:
                raw_len = self._recv_exact(conn.sock, 4)
                (blen,) = struct.unpack("<I", raw_len)
                if blen < 10 or blen > (1 << 26):
                    raise ConnectionError(f"bad frame length {blen}")
                body = self._recv_exact(conn.sock, blen)
                (crc,) = struct.unpack("<I",
                                       self._recv_exact(conn.sock, 4))
                if _crc(raw_len + body) != crc:
                    # ProtocolV2 crc mode: corrupt frame kills the
                    # session; replay redelivers after reconnect
                    raise ConnectionError("frame crc mismatch")
                seq, tid = struct.unpack("<QH", body[:10])
                payload = body[10:]
                if tid == ACK_TYPE:
                    if len(payload) != 8:
                        raise ConnectionError("malformed ACK frame")
                    (acked,) = struct.unpack("<Q", payload)
                    with self._lock:
                        q = self._unacked.get(peer)
                        while q and q[0][0] <= acked:
                            q.popleft()
                    continue
                deliver = False
                with self._lock:
                    if seq > self._in_seq.get(peer, 0):
                        self._in_seq[peer] = seq
                        deliver = True  # else: replayed dup, drop
                try:
                    conn.send_frame(0, ACK_TYPE,
                                    struct.pack("<Q", seq))
                except (OSError, ConnectionError):
                    pass
                if deliver:
                    cls = _MSG_TYPES.get(tid)
                    handler = self._handlers.get(tid)
                    if cls is not None and handler is not None:
                        try:
                            handler(peer,
                                    cls.decode_payload(Decoder(payload)))
                        except Exception as e:  # poison message: the
                            # frame was crc-valid and is already acked;
                            # contain the blast radius to this message
                            # (fast dispatch must not kill the session)
                            from ..utils.log import g_log
                            g_log.dout("msgr", 0,
                                       f"dispatch error from {peer} "
                                       f"type={tid:#x} seq={seq}: {e!r}")
        except (OSError, ConnectionError):
            pass
        finally:
            conn.close()
            with self._lock:
                if self._conns.get(peer) is conn:
                    del self._conns[peer]

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._stopping = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
