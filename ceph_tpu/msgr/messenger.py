"""Messenger — typed, CRC-protected, lossless-peer RPC.

Rebuild of the reference's wire layer (ref: src/msg/Messenger.h
Messenger/Connection/Dispatcher; src/msg/async/AsyncMessenger.cc —
listen + per-connection state machines; src/msg/async/ProtocolV2.cc —
banner exchange, crc-protected frame segments, and RESET/reconnect
semantics; src/messages/*.h — typed Message subclasses). The control
plane the sim runs in-process gets a real cross-process transport
here: the native EC shim already crosses processes for DATA (its unix
socket), this module is the typed CONTROL path (the role MOSDPing /
MOSDPGLog / mon messages play).

Scope and mapping (SURVEY §2.5/§5): bulk data movement between chips
is ICI/DCN collectives, NOT this messenger — so this layer stays small
and correctness-first. Implemented faithfully:

* banner + identity handshake carrying the receiver's last-seen
  sequence number per peer, so a reconnect resumes exactly where the
  stream broke (the lossless_peer policy's replay);
* frames `[u32 len][u64 seq][u16 type][payload][u32 crc32c]` — the
  crc covers everything before it; a corrupt frame kills the
  connection (ProtocolV2 crc mode behavior), and the sender's replay
  queue redelivers on reconnect;
* explicit ACKs retire the sender's unacked queue; receivers dedup by
  (peer, seq) so redelivery is exactly-once upward;
* a Dispatcher callback per message type (ms_fast_dispatch role);
* SECURE mode (ref: src/msg/async/ProtocolV2.cc secure session
  handshake + cephx): a Messenger built with a shared secret
  negotiates mode at handshake (strict — a secure endpoint refuses a
  crc peer, the anti-downgrade stance), mutually authenticates with
  an HMAC challenge/response over both sides' nonces (the cephx
  role, collapsed to one pre-shared key), derives a per-connection
  AES-256-GCM session key via HKDF(secret, nonce_c||nonce_s), and
  seals every frame `[u32 len][12B nonce][AES-GCM(seq|type|payload)]`
  with the length as AAD. Nonces are direction-prefixed counters
  (never reused under one key); a tampered frame fails the GCM tag
  and kills the session exactly like a crc mismatch — replay heals.

* COMPRESSION (ref: ProtocolV2 compression handshake +
  src/compressor/): endpoints offer an algorithm at handshake;
  active only when both offer the same one (a mismatch downgrades to
  plain — compression is an optimization, unlike the security mode).
  Per-message: payloads under a min size or that don't shrink ship
  plain, flagged in the type field's high bit. Composes with both
  modes — compression happens before the crc/seal covers the bytes,
  a garbled compressed body kills the session like a crc mismatch,
  and in secure mode the negotiated byte is bound into the auth
  proof so an active tamperer cannot strip it.

Threading model (ref: src/msg/async/Stack.h Worker/NetworkStack —
the AsyncMessenger epoll worker pool): N REACTOR worker threads per
messenger, each running a `selectors` (epoll on Linux) event loop.
Connections are bound to a reactor ROUND-ROBIN at handshake
completion (accept and dial alike) and stay there for life — all of a
connection's socket I/O happens on its one reactor, so per-connection
frame order needs no cross-thread coordination. The contract:

* READS are nonblocking and batched: one wakeup drains the socket
  into a per-connection buffer and parses every complete frame in it
  (wire format identical to the blocking era — the frame bytes are
  pinned bit-for-bit by tests/test_msgr_frames.py).
* WRITES go through a per-connection WRITE QUEUE: send_frame seals/
  CRCs the frame (in queue order, under the connection write lock —
  nonce counters never reorder) and appends the iovec; whoever holds
  the lock gather-flushes the whole queue in ONE sendmsg (many frames
  per syscall). A socket that won't drain arms EVENT_WRITE and the
  reactor resumes from the exact byte. Senders block on a byte-budget
  backpressure cap (never reactor threads — they may hold frames
  other connections are waiting on).
* DISPATCH is fast by default (the ms_fast_dispatch role): handlers
  run inline on the reactor, so they must never wait for another
  frame of the SAME messenger to make progress. Handlers that block
  on remote replies (the OSD's map fold runs a whole reconcile)
  register with fast=False and run on the messenger's dispatch
  thread instead — a reactor never blocks, so rpc replies always
  drain even while a slow handler is mid-flight.
* A standalone _Conn with no reactor (the frame-capture tests, the
  handshake window before binding) falls back to blocking writes —
  same bytes, same order.
"""

from __future__ import annotations

import selectors
import socket
import struct
import threading
import time as _time_mod
from collections import deque

from ..csum.reference import ceph_crc32c, ceph_crc32c_iov
from ..utils.encoding import Decoder, Encoder
from ..utils.flight_recorder import current_sampled as _ftrace_active
from ..utils.flight_recorder import trace_span as _ftrace_span
from ..utils.perf_counters import PerfCountersBuilder


def msgr_perf_counters():
    """The messenger's counter schema (ref: AsyncMessenger's
    msgr_send/recv counters in src/msg/async/Stack.h, dumped as the
    `AsyncMessenger::Worker-*` loggers). One instance per Messenger;
    a daemon nests it under "msgr" in its perf dump."""
    return (PerfCountersBuilder("msgr")
            .add_u64_counter("msg_tx", "logical messages sent")
            .add_u64_counter("msg_rx", "messages delivered upward")
            .add_u64_counter("frames_tx", "wire frames written")
            .add_u64_counter("frames_rx", "wire frames read")
            .add_u64_counter("bytes_tx", "wire bytes written")
            .add_u64_counter("bytes_rx", "wire bytes read")
            .add_u64_counter("segments_tx",
                             "gather segments written (zero-copy iov)")
            .add_u64_counter("acks_tx", "cumulative ACK frames sent")
            .add_u64_counter("acks_rx", "cumulative ACK frames received")
            .add_u64_counter("dup_rx", "replayed duplicates dropped")
            .add_u64_counter("reconnects", "outbound dials completed")
            .add_u64_counter("replayed", "unacked frames replayed")
            .add_u64_counter("tx_compressed", "frames compressed on tx")
            .add_u64_counter("rx_compressed", "frames inflated on rx")
            .add_time_avg("crc_time", "frame crc32c compute (crc mode)")
            .add_time_avg("seal_time",
                          "AEAD seal incl. staging (secure mode)",
                          hist=True)
            .add_time_avg("open_time", "AEAD open (secure mode)")
            # reactor event-loop occupancy (the AsyncMessenger worker
            # counters: msgr_active_connections / worker event time)
            .add_u64_counter("reactor_loops",
                             "reactor loop iterations (select returns)")
            .add_u64_counter("reactor_wakeups",
                             "loop wakeups forced by the wake pipe "
                             "(cross-thread register/arm-write)")
            .add_time_avg("reactor_stall_time",
                          "time per loop iteration spent OUT of "
                          "select (dispatch + flush = loop lag for "
                          "concurrent events)")
            .add_u64("writeq_depth",
                     "bytes queued across connection write queues")
            .add_u64_counter("writeq_flushes",
                             "gather-flush sendmsg calls")
            .add_u64_counter("writeq_stalls",
                             "sends that blocked on the write-queue "
                             "byte budget")
            .add_time_avg("writeq_stall_time",
                          "backpressure wait per stalled send")
            .create_perf_counters())

BANNER = b"ceph_tpu msgr v2\n"
ACK_TYPE = 0
#: cumulative-ACK coalescing: ack every Nth delivered frame inline,
#: and let the ack flusher cover the tail within ~20 ms. ACK frames
#: are bit-identical to the per-frame era (same [seq 0][type 0][u64]
#: format — the u64 is cumulative, which the sender's `<=` retire loop
#: always honored), so mixed old/new peers interoperate. Acks only
#: retire the sender's replay queue — replies never wait on them — so
#: the delay costs nothing while cutting the rpc pattern's frame count
#: by a third.
ACK_BATCH = 8
MODE_CRC = 0
MODE_SECURE = 1
_GCM_TAG = 16
_NONCE = 12

# on-wire compression (ref: src/msg/async/ProtocolV2.cc compression
# handshake + src/compressor/): negotiated per connection, composes
# with BOTH crc and secure mode (the payload is compressed before the
# crc/seal covers it, so integrity always checks the wire bytes).
# The frame's type field carries the per-message flag in its high bit
# — small or incompressible payloads ship plain on a compressed
# connection, exactly the reference's min-size behavior.
COMP_NONE = 0
COMP_ZLIB = 1
_COMP_IDS = {None: COMP_NONE, "zlib": COMP_ZLIB}
_COMP_FLAG = 0x8000
_COMPRESS_MIN = 128          # don't bloat tiny frames
_DECOMP_MAX = 1 << 26        # decompression-bomb ceiling (= frame cap)

_MSG_TYPES: dict[int, type] = {}


class _SecureBox:
    """Per-connection AES-256-GCM sealer/opener. One direction-unique
    4-byte prefix + 8-byte little-endian counter per nonce — counters
    are advanced under the connection's write lock, so a nonce is
    never reused under the session key."""

    def __init__(self, key: bytes, tx_prefix: bytes, rx_prefix: bytes):
        from ..auth.aead import AEAD
        self._gcm = AEAD(key)
        self._tx_prefix = tx_prefix
        self._rx_prefix = rx_prefix
        self._tx_ctr = 0

    def seal(self, plain: bytes, aad: bytes) -> bytes:
        nonce = self._tx_prefix + self._tx_ctr.to_bytes(8, "little")
        self._tx_ctr += 1
        return nonce + self._gcm.encrypt(nonce, plain, aad)

    def open(self, body: bytes, aad: bytes) -> bytes:
        from ..auth.aead import InvalidTag
        if len(body) < _NONCE + _GCM_TAG:
            raise ConnectionError("secure frame too short")
        nonce, ct = body[:_NONCE], body[_NONCE:]
        if nonce[:4] != self._rx_prefix:
            raise ConnectionError("secure frame nonce from wrong "
                                  "direction")
        try:
            return self._gcm.decrypt(nonce, ct, aad)
        except InvalidTag:
            # tampered/garbled ciphertext kills the session, exactly
            # like a crc mismatch in crc mode; replay redelivers
            raise ConnectionError("secure frame auth tag mismatch")


def _derive_key(secret: bytes, nonce_c: bytes, nonce_s: bytes) -> bytes:
    from ..auth.aead import hkdf_sha256
    return hkdf_sha256(secret, salt=nonce_c + nonce_s,
                       info=b"ceph_tpu msgr v2 secure session")


#: fixed per-role nonce prefixes: deterministic direction separation
#: (random nonce slices would collide with p=2^-32 per connection and
#: alias both directions' counter spaces under ONE AES-GCM key)
_PREFIX_SRV = b"srv\x00"
_PREFIX_CLI = b"cli\x00"


def _auth_proof(secret: bytes, role: bytes, nonce_c: bytes,
                nonce_s: bytes, name: str,
                seen_c: int, seen_s: int, offers: bytes) -> bytes:
    """The proofs bind EVERY plaintext handshake field — name, both
    last-seen sequence numbers, and both sides' RAW compression
    offers — not just the nonces: an unauth'd peer_seen would let an
    active tamperer inflate it and silently flush the victim's
    unacked replay queue. The offers must be bound raw (client's,
    server's — not the derived result): a tamperer flipping both
    offer bytes to 'none' would leave the negotiated RESULT matching
    on both sides, so only the offers themselves expose the strip."""
    import hashlib
    import hmac
    return hmac.new(secret,
                    role + nonce_c + nonce_s + name.encode()
                    + seen_c.to_bytes(8, "little")
                    + seen_s.to_bytes(8, "little")
                    + offers,
                    hashlib.sha256).digest()


def register_message(cls):
    """Class decorator: register a Message subclass by its type_id."""
    tid = cls.type_id
    if tid in _MSG_TYPES and _MSG_TYPES[tid] is not cls:
        raise ValueError(f"message type {tid} already registered")
    if tid == ACK_TYPE:
        raise ValueError("type 0 is reserved for ACK")
    if tid >= _COMP_FLAG:
        raise ValueError("type ids above 0x7FFF collide with the "
                         "compression flag bit")
    _MSG_TYPES[tid] = cls
    return cls


class Message:
    """Typed payload (the Message subclass contract): subclasses set
    type_id and implement encode_payload/decode_payload."""

    type_id: int = -1

    def encode_payload(self, e: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def decode_payload(cls, d: Decoder) -> "Message":
        raise NotImplementedError


_crc32c_impl = None


def _crc_impl():
    # frame CRCs run per message on the hot wire path: use the native
    # C codec's crc32c (bit-identical to ceph_crc32c — pinned by
    # tests/test_native.py) instead of the per-byte python reference.
    # Resolved LAZILY and only when the .so is ALREADY BUILT: import
    # must never trigger a compile (parallel `make -B` races corrupt
    # the .so for concurrent bench subprocesses).
    global _crc32c_impl
    if _crc32c_impl is None:
        impl = ceph_crc32c
        try:
            from .. import native
            if native.ready():
                native.native_crc32c(0, b"probe")
                impl = native.native_crc32c
        except Exception:          # noqa: BLE001 — optional native lib
            pass
        _crc32c_impl = impl
    return _crc32c_impl


def _crc(data: bytes) -> int:
    return int(_crc_impl()(0xFFFFFFFF, data)) & 0xFFFFFFFF


def _crc_iov(parts) -> int:
    """Frame CRC as a seeded continuation over segments — identical to
    _crc(join(parts)) with no join (the running-CRC form both the
    python reference and the native codec are chainable in)."""
    return ceph_crc32c_iov(0xFFFFFFFF, parts, update=_crc_impl())


def _flatten(payload) -> bytes:
    """Materialize a payload (bytes-like or segment list) into ONE
    contiguous bytes. This is the single choke point where the framing
    path may copy payload bytes — the zero-copy smoke test counts
    calls to it (crc mode: zero; secure/compress: one staged buffer
    per frame)."""
    if isinstance(payload, (list, tuple)):
        return b"".join(payload)
    return bytes(payload)


def _payload_len(payload) -> int:
    if isinstance(payload, (list, tuple)):
        return sum(len(p) for p in payload)
    return len(payload)


def _set_nodelay(sock: socket.socket) -> None:
    if sock.family == socket.AF_INET:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # generous kernel buffers: a 512 KiB batched write frame should
    # leave in ONE sendmsg, not ping-pong through EAGAIN/arm-write
    # reactor cycles against the ~208 KiB default
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 20)
        except OSError:
            pass


def _sendmsg_all(sock: socket.socket, parts: list) -> None:
    """Gather-write the iovec fully (sendmsg may send partially under
    pressure; resume from the exact byte like sendall would)."""
    views = [memoryview(p) for p in parts if len(p)]
    total = sum(len(v) for v in views)
    sent = sock.sendmsg(views)
    while sent < total:
        total -= sent
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0
        sent = sock.sendmsg(views)


#: reactor threads must never block on another connection's write
#: budget (they may hold frames that connection is waiting on): the
#: loop marks itself and _enqueue skips the backpressure wait
_TLS = threading.local()

#: per-connection write-queue byte budget: senders beyond it block
#: until the reactor drains below half (the ms write-queue throttle
#: role). Generous — the op window bounds steady state well below it.
_WQ_HIGH = 16 << 20
#: max iovec parts per gather-flush sendmsg (IOV_MAX headroom)
_WQ_IOV = 512


class _Reactor(threading.Thread):
    """One epoll worker (ref: src/msg/async/EventCenter): owns a
    selector; every registered socket's events are handled on this
    thread. Cross-thread mutations (register, arm-write, close) are
    marshalled through call() + a wake pipe — the selector itself is
    touched only from the loop."""

    def __init__(self, name: str, perf=None):
        super().__init__(daemon=True, name=name)
        self.sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self.sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._calls: deque = deque()
        self._clock = threading.Lock()
        self._stopping = False
        self.perf = perf
        self._owned: set = set()     # sockets to close at stop
        self.start()

    # -- cross-thread surface ------------------------------------------------

    def call(self, fn) -> None:
        """Run fn() on the reactor thread (next loop iteration)."""
        with self._clock:
            self._calls.append(fn)
        self.wakeup()

    def wakeup(self) -> None:
        try:
            self._wake_w.send(b"\0")
        except (BlockingIOError, OSError):
            pass                      # pipe full = already waking

    def stop(self) -> None:
        self._stopping = True
        self.wakeup()

    # -- loop-thread surface -------------------------------------------------

    def register(self, sock: socket.socket, events: int, cb) -> None:
        """cb(mask) is invoked on this thread for every event."""
        self._owned.add(sock)
        try:
            self.sel.register(sock, events, cb)
        except (KeyError, ValueError, OSError):
            pass

    def unregister(self, sock: socket.socket) -> None:
        self._owned.discard(sock)
        try:
            self.sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass

    def set_events(self, sock: socket.socket, events: int) -> None:
        try:
            key = self.sel.get_key(sock)
            if key.events != events:
                self.sel.modify(sock, events, key.data)
        except (KeyError, ValueError, OSError):
            pass                      # unregistered/closed meanwhile

    def run(self) -> None:
        _TLS.in_reactor = True
        perf = self.perf
        while not self._stopping:
            try:
                events = self.sel.select(timeout=0.5)
            except OSError:
                if self._stopping:
                    break
                continue
            t0 = _time_mod.perf_counter()
            woke = 0
            for key, mask in events:
                if key.data is None:          # the wake pipe
                    woke = 1
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                    continue
                try:
                    key.data(mask)
                except Exception:   # noqa: BLE001 — one connection's
                    pass            # failure must not kill the loop
            while True:
                with self._clock:
                    if not self._calls:
                        break
                    fn = self._calls.popleft()
                try:
                    fn()
                except Exception:   # noqa: BLE001
                    pass
            if perf is not None:
                perf.inc_many((("reactor_loops", 1),
                               ("reactor_wakeups", woke)))
                perf.tinc("reactor_stall_time",
                          _time_mod.perf_counter() - t0)
        for sock in list(self._owned):
            try:
                sock.close()
            except OSError:
                pass
        try:
            self.sel.unregister(self._wake_r)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self.sel.close()
            self._wake_r.close()
            self._wake_w.close()
        except OSError:
            pass


class _Conn:
    """One live socket + replay state toward one peer."""

    def __init__(self, sock: socket.socket, box: _SecureBox | None = None,
                 peer_inst: bytes = b"", comp: int = COMP_NONE,
                 stats: dict | None = None,
                 stats_lock: threading.Lock | None = None,
                 perf=None, flow: dict | None = None,
                 flow_lock: threading.Lock | None = None):
        self.sock = sock
        self.wlock = threading.Lock()
        self.alive = True
        self.box = box
        self.perf = perf
        # per-PEER flow ledger (r22): shared with the Messenger so the
        # numbers survive reconnects — the ledger is keyed by peer
        # name, the conn just holds its entry. flow_lock is a leaf
        # lock (never taken while acquiring another).
        self.flow = flow
        self.flow_lock = flow_lock
        # receive-side cumulative-ack cursor: highest peer seq this
        # side has ACKED on this conn (reader + ack flusher both
        # advance it; acks are idempotent so the benign race costs at
        # most one duplicate ack)
        self.acked_out = 0
        self.comp = comp            # negotiated compression algo id
        self.stats = stats if stats is not None else {}
        self.stats_lock = stats_lock or threading.Lock()
        # which peer INCARNATION this conn authenticated: frames from
        # a conn whose incarnation is no longer current must never
        # reach the session state (see _on_frame)
        self.peer_inst = peer_inst
        # reactor binding (None = standalone blocking writes — the
        # pre-handshake window and the frame-capture test harness)
        self.reactor: _Reactor | None = None
        self._rx = bytearray()      # unparsed inbound bytes
        self._wq: deque = deque()   # outbound iovec parts, wire-ready
        self._wq_bytes = 0
        self._wcond = threading.Condition(self.wlock)
        self._write_armed = False
        self._closed = False

    def send_frame(self, seq: int, type_id: int, payload) -> None:
        """`payload` is bytes-like OR a segment list (Encoder.segments
        output). Wire bytes are bit-identical either way; the list form
        never copies the payload in crc mode (gather-write + running
        CRC), and stages exactly one contiguous buffer in secure/
        compressed mode (the seal/deflate input). With a reactor bound
        the frame is QUEUED (sealed/CRCed in queue order) and flushed
        opportunistically — many frames coalesce into one sendmsg."""
        segs = list(payload) if isinstance(payload, (list, tuple)) \
            else [payload]
        plen = sum(len(s) for s in segs)
        is_ack = type_id == ACK_TYPE
        if self.comp == COMP_ZLIB and plen >= _COMPRESS_MIN:
            import zlib
            packed = zlib.compress(_flatten(segs), 1)
            if len(packed) < plen:   # only when it helps
                segs = [packed]
                plen = len(packed)
                type_id |= _COMP_FLAG
                with self.stats_lock:
                    self.stats["tx_compressed"] = \
                        self.stats.get("tx_compressed", 0) + 1
                if self.perf is not None:
                    self.perf.inc("tx_compressed")
        if self.box is None:
            # [u32 len][u64 seq][u16 type] packs to the same 14 bytes
            # the two-step concat produced; the crc is a seeded
            # continuation over header + payload segments — no join
            hdr = struct.pack("<IQH", 10 + plen, seq, type_id)
            t0 = _time_mod.perf_counter() if self.perf is not None else 0.0
            crc = struct.pack("<I", _crc_iov([hdr] + segs))
            if self.perf is not None:
                self.perf.tinc("crc_time",
                               _time_mod.perf_counter() - t0)
            with self.wlock:
                if self.reactor is None:
                    _sendmsg_all(self.sock, [hdr] + segs + [crc])
                else:
                    self._enqueue_locked([hdr] + segs + [crc])
            wire = 14 + plen + 4
            nseg = len(segs)
        else:
            # r15: when a sampled trace context is active on this
            # thread (an op reply sealing inside the op's dynamic
            # extent), the AEAD seal records as a crypto span — one
            # contextvar read per frame otherwise
            with self.wlock:
                # seal under the lock: the nonce counter must advance
                # in transmit order or a reordered pair would reuse
                # one. AEAD needs contiguous input: stage ONE buffer.
                hdr = struct.pack(
                    "<I", _NONCE + 10 + plen + _GCM_TAG)
                t0 = _time_mod.perf_counter() \
                    if self.perf is not None else 0.0
                if _ftrace_active() is not None:
                    with _ftrace_span("msgr.seal", nbytes=plen):
                        plain = _flatten(
                            [struct.pack("<QH", seq, type_id)] + segs)
                        sealed = self.box.seal(plain, hdr)
                else:
                    plain = _flatten(
                        [struct.pack("<QH", seq, type_id)] + segs)
                    sealed = self.box.seal(plain, hdr)
                if self.perf is not None:
                    self.perf.tinc("seal_time",
                                   _time_mod.perf_counter() - t0)
                if self.reactor is None:
                    _sendmsg_all(self.sock, [hdr, sealed])
                else:
                    self._enqueue_locked([hdr, sealed])
            wire = 4 + _NONCE + 10 + plen + _GCM_TAG
            nseg = 1
        if self.perf is not None:
            self.perf.inc_many((("frames_tx", 1), ("bytes_tx", wire),
                                ("segments_tx", nseg))
                               + ((("acks_tx", 1),) if is_ack else ()))
        if self.flow is not None:
            with self.flow_lock:
                self.flow["frames_tx"] += 1
                self.flow["bytes_tx"] += wire

    # -- write queue (reactor-bound conns) ------------------------------------

    def _enqueue_locked(self, parts: list) -> None:
        """Append wire-ready parts and flush opportunistically. Caller
        holds wlock. Blocks on the byte budget — except on reactor
        threads, which must never wait on another conn's drain."""
        if not self.alive:
            raise ConnectionError("connection closed")
        if (self._wq_bytes > _WQ_HIGH
                and not getattr(_TLS, "in_reactor", False)):
            t0 = _time_mod.perf_counter()
            while self.alive and self._wq_bytes > _WQ_HIGH // 2:
                self._wcond.wait(0.2)
            dt = _time_mod.perf_counter() - t0
            if self.perf is not None:
                self.perf.inc("writeq_stalls")
                self.perf.tinc("writeq_stall_time", dt)
            if self.flow is not None:
                with self.flow_lock:
                    self.flow["stalls"] += 1
                    self.flow["stall_time_s"] += dt
            if not self.alive:
                raise ConnectionError("connection closed")
        for p in parts:
            if len(p):
                self._wq.append(memoryview(p))
                self._wq_bytes += len(p)
        self._flush_locked()

    def _flush_locked(self) -> None:
        """Gather-write as much of the queue as the socket takes (many
        frames per sendmsg). Caller holds wlock. A full socket arms
        EVENT_WRITE; the reactor resumes from the exact byte."""
        while self._wq:
            iov = []
            n = 0
            for v in self._wq:
                iov.append(v)
                n += 1
                if n >= _WQ_IOV:
                    break
            try:
                sent = self.sock.sendmsg(iov)
            except (BlockingIOError, InterruptedError):
                self._arm_write_locked()
                return
            except OSError:
                # socket died with frames queued: they are all still
                # in the sender's unacked queue — replay redelivers
                # after the reconnect. The reactor reaps the conn.
                self.alive = False
                self._wcond.notify_all()
                if self.reactor is not None:
                    self.reactor.wakeup()
                return
            if self.perf is not None:
                self.perf.inc("writeq_flushes")
            self._wq_bytes -= sent
            while sent:
                head = self._wq[0]
                if sent >= len(head):
                    sent -= len(head)
                    self._wq.popleft()
                else:
                    self._wq[0] = head[sent:]
                    sent = 0
        if self._wq_bytes <= _WQ_HIGH // 2:
            self._wcond.notify_all()
        if self.perf is not None:
            self.perf.set("writeq_depth", self._wq_bytes)
        if self.flow is not None:
            with self.flow_lock:
                self.flow["writeq_bytes"] = self._wq_bytes
                self.flow["writeq_frames"] = len(self._wq)

    def _arm_write_locked(self) -> None:
        if self._write_armed or self.reactor is None:
            return
        self._write_armed = True
        r, sock = self.reactor, self.sock
        r.call(lambda: r.set_events(
            sock, selectors.EVENT_READ | selectors.EVENT_WRITE))

    def _on_writable(self) -> None:
        """Reactor: socket drained — flush more, disarm when empty."""
        with self.wlock:
            self._flush_locked()
            if not self._wq and self._write_armed:
                self._write_armed = False
                self.reactor.set_events(self.sock,
                                        selectors.EVENT_READ)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        with self.wlock:
            self._wcond.notify_all()   # unblock backpressured senders
        r = self.reactor
        if r is None:
            self._close_fd()
        else:
            # the fd itself closes ON the reactor: closing here would
            # let the OS reuse the number while the selector still
            # maps it — events would route to the wrong connection
            r.call(self._reactor_close)

    def _reactor_close(self) -> None:
        if self.reactor is not None:
            self.reactor.unregister(self.sock)
        self._close_fd()

    def _close_fd(self) -> None:
        with self.wlock:
            if self._closed:
                return
            self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


class Messenger:
    """Bind, connect, send typed messages, dispatch callbacks.

    Lossless-peer semantics: every logical message gets a sequence
    number; unacked messages survive connection death and are replayed
    after the automatic reconnect (send() never silently drops)."""

    def __init__(self, name: str, host: str = "127.0.0.1",
                 secret: bytes | None = None,
                 compress: str | None = None,
                 workers: int | None = None,
                 uds: bool = False):
        """`secret` switches the endpoint to SECURE mode: every
        connection mutually authenticates against the shared secret
        and encrypts frames with a per-connection AES-GCM key. A
        secure endpoint refuses crc peers and vice versa (strict
        negotiation — no downgrade path). `compress` ("zlib") offers
        per-connection compression: active only when BOTH endpoints
        offer the same algorithm (an optimization, so a mismatch
        downgrades to plain rather than refusing); in secure mode the
        negotiated byte is bound into the auth proof so it cannot be
        tampered down. `workers` sets the reactor thread count (the
        ms_async_op_threads role; default 1, or
        $CEPH_TPU_MSGR_WORKERS) — connections bind round-robin.
        `uds` listens on a Unix-domain socket instead of loopback TCP
        (same frames, same handshake — only the byte carrier changes;
        ~2.5x the bulk throughput of the loopback TCP stack on this
        kernel). The address book carries ("unix", path) tuples, so
        mixed TCP/UDS endpoints interoperate peer by peer."""
        self.name = name
        self.secret = secret
        self.compress = compress
        self._comp_id = _COMP_IDS[compress]
        self.stats: dict[str, int] = {}
        self._stats_lock = threading.Lock()
        # per-messenger counters (a daemon nests this under "msgr" in
        # its perf dump; ref: the AsyncMessenger worker loggers)
        self.perf = msgr_perf_counters()
        self.mode = MODE_SECURE if secret is not None else MODE_CRC
        # instance cookie (ref: ProtocolV2 client/server cookies +
        # RESET_SESSION): a rebooted process reuses its NAME but not
        # its sequence space — peers detect the new cookie at
        # handshake and reset the receive direction, else every frame
        # from the new incarnation would be dropped as a replayed
        # duplicate by the max-seq dedup
        import os as _os
        self.instance_nonce = _os.urandom(8)
        self._peer_nonce: dict[str, bytes] = {}
        self._handlers: dict[int, callable] = {}
        self._lock = threading.Lock()
        # one lock per PEER held across seq-assignment + transmit:
        # frames must hit the socket in sequence order or the
        # receiver's max-seq dedup would discard reordered messages,
        # and concurrent connects would race adopting sockets
        self._peer_locks: dict[str, threading.RLock] = {}
        # per-peer-name state (the lossless session, not the socket):
        self._out_seq: dict[str, int] = {}
        self._unacked: dict[str, deque] = {}   # (seq, type, payload)
        self._in_seq: dict[str, int] = {}      # last delivered seq
        self._conns: dict[str, _Conn] = {}
        self._addr_of: dict[str, tuple] = {}
        self._blocked: set[str] = set()        # partition injection
        # ms_inject_socket_failures analog: every Nth send kills the
        # live socket first (0 = off); _inject_fired counts teardowns
        self._inject_every = 0
        self._inject_count = 0
        self._inject_fired = 0
        # ms_inject_delay analog: uniform [0, max_ms] sleep before
        # every Nth transmit (0 = off) — injects timing skew and
        # CROSS-peer reordering (within one peer the per-peer lock +
        # seq assignment after the sleep keep frames in order); it
        # stresses timeout boundaries, not the seq dedup
        self._delay_every = 0
        self._delay_max_ms = 0.0
        self._delay_count = 0
        self._delay_fired = 0
        # r22 link-degrade injection: a PER-PEER one-way delay (base +
        # uniform jitter, ms) applied on the sender's dispatch path
        # before every transmit toward that peer — a directed slow
        # LINK, where set_inject_delay is a slow PROCESS. Reactor
        # threads never sleep, so fast-dispatch replies (pongs) pass
        # undelayed: the delay lands on exactly one direction of one
        # link, which is what gives the health check its sharp
        # attribution.
        self._link_delay: dict[str, tuple[float, float]] = {}
        self._link_delay_fired = 0
        # r22 per-peer flow ledger: bytes/frames both ways, write-queue
        # stalls, live queue depth — same counters the perf logger
        # aggregates, kept per peer so traffic and RTT share a key.
        # Entries persist across reconnects (session scope, like
        # _out_seq); _flow_lock is a leaf lock.
        self._flow: dict[str, dict] = {}
        self._flow_lock = threading.Lock()
        # injection decisions come from a PER-MESSENGER RNG, never the
        # global `random`: a thrash run that logs its seed must replay
        # the same delay schedule, and the global stream is perturbed
        # by every other random consumer in the process
        import random as _random
        self._inject_rng = _random.Random()
        self._stopping = False
        # the reactor pool (ref: AsyncMessenger's Worker threads):
        # every connection's socket I/O runs on exactly one of these
        if workers is None:
            import os as _os
            workers = int(_os.environ.get("CEPH_TPU_MSGR_WORKERS",
                                          "1") or 1)
        self._reactors = [_Reactor(f"msgr-{name}-r{i}", perf=self.perf)
                          for i in range(max(1, int(workers)))]
        self._rr = 0                 # round-robin binding cursor
        self._uds_path = None
        if uds:
            import os as _os
            import tempfile as _tempfile
            # short path (AF_UNIX caps at ~107 bytes), unique per
            # incarnation — a revived daemon must not collide with
            # its corpse's socket file
            self._uds_path = _os.path.join(
                _tempfile.gettempdir(),
                f"cmsgr-{_os.getpid():x}-"
                f"{self.instance_nonce[:4].hex()}.sock")
            self._listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            self._listener.bind(self._uds_path)
            self._listener.listen(128)
            self.addr = ("unix", self._uds_path)
        else:
            self._listener = socket.create_server((host, 0))
            self.addr = self._listener.getsockname()
        self._listener.setblocking(False)
        r0 = self._reactors[0]
        r0.call(lambda: r0.register(self._listener,
                                    selectors.EVENT_READ,
                                    self._accept_ready))
        # slow-dispatch queue (the DispatchQueue role): handlers
        # registered fast=False run here so a blocking fold can never
        # stall a reactor. Started lazily with the first slow handler.
        self._dispatch_q = None
        # delayed-ack flusher: covers frames the inline every-Nth ack
        # didn't reach (see ACK_BATCH); event-driven so an idle
        # messenger sleeps
        self._ack_event = threading.Event()
        self._ack_thread = threading.Thread(target=self._ack_loop,
                                            daemon=True)
        self._ack_thread.start()

    # -- dispatch ------------------------------------------------------------

    def register_handler(self, type_id: int, fn,
                         fast: bool = True) -> None:
        """fn(peer_name: str, msg: Message). `fast` handlers run
        INLINE on the connection's reactor (ms_fast_dispatch): they
        must never wait for another frame of this messenger to make
        progress. Handlers that can block on remote replies (a map
        fold that runs a reconcile) pass fast=False and run on the
        messenger's dispatch thread — per-peer order among slow
        frames is preserved (one FIFO), order RELATIVE to fast frames
        of the same connection is not (exactly the reference's
        fast-vs-queued dispatch contract)."""
        self._handlers[type_id] = (fn, fast)
        if not fast and self._dispatch_q is None:
            import queue
            self._dispatch_q = queue.SimpleQueue()
            threading.Thread(target=self._dispatch_loop,
                             daemon=True).start()

    def _dispatch_loop(self) -> None:
        import queue
        while not self._stopping:
            try:
                fn, peer, cls, payload = self._dispatch_q.get(
                    timeout=0.5)
            except queue.Empty:
                continue
            try:
                fn(peer, cls.decode_payload(Decoder(payload)))
            except Exception as e:  # noqa: BLE001 — poison message:
                # already acked; contain the blast radius (same rule
                # as fast dispatch)
                from ..utils.log import g_log
                g_log.dout("msgr", 0,
                           f"dispatch error from {peer} "
                           f"type={cls.type_id:#x}: {e!r}")

    # -- connection management ----------------------------------------------

    def _accept_ready(self, mask: int) -> None:
        """Reactor 0: the listener is readable — accept everything
        pending; each new socket handshakes on its own (short-lived)
        thread, then binds to a reactor round-robin."""
        while not self._stopping:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                # transient failure (e.g. EMFILE): the listener stays
                # registered — the next readable event retries rather
                # than silently going deaf
                return
            threading.Thread(target=self._handshake_in, args=(sock,),
                             daemon=True).start()

    def _check_incarnation(self, peer: str, nonce: bytes) -> None:
        """A changed instance cookie = the peer rebooted: its sequence
        space restarted, so our receive cursor must too (the
        RESET_SESSION role). Our own send state stays — the fresh peer
        reports seen=0 and triggers a full replay of unacked."""
        with self._lock:
            old = self._peer_nonce.get(peer)
            if old is not None and old != nonce:
                self._in_seq.pop(peer, None)
            self._peer_nonce[peer] = nonce

    def _handshake_in(self, sock: socket.socket) -> None:
        box = None
        try:
            # disable Nagle: frames go out as several small sends
            # (header, then payload); coalescing them behind delayed
            # ACKs costs tens of ms PER FRAME on the rpc path (the
            # reference sets TCP_NODELAY on every messenger socket;
            # ref: AsyncConnection socket options ms_tcp_nodelay).
            # Unix-domain sockets have no Nagle to disable.
            _set_nodelay(sock)
            if self._recv_exact(sock, len(BANNER)) != BANNER:
                sock.close()
                return
            nlen = struct.unpack("<H", self._recv_exact(sock, 2))[0]
            peer = self._recv_exact(sock, nlen).decode()
            if peer in self._blocked:
                sock.close()      # partitioned: refuse the dial
                return
            peer_inst = self._recv_exact(sock, 8)
            # symmetric handshake: both sides exchange their last-seen
            # sequence so BOTH replay their unacked queues — an
            # acceptor has stranded messages too after a reconnect
            (peer_seen,) = struct.unpack(
                "<Q", self._recv_exact(sock, 8))
            peer_mode = self._recv_exact(sock, 1)[0]
            if peer_mode != self.mode:
                # strict negotiation: refusing the mismatch beats
                # silently downgrading an endpoint that demands secure
                sock.close()
                return
            peer_comp = self._recv_exact(sock, 1)[0]
            # compression is an optimization: on iff both offer the
            # same algorithm, else plain (no refusal)
            comp = self._comp_id if peer_comp == self._comp_id \
                else COMP_NONE
            nonce_c = b""
            if self.mode == MODE_SECURE:
                nonce_c = self._recv_exact(sock, 16)
            me = self.name.encode()
            sock.sendall(BANNER + self.instance_nonce
                         + struct.pack("<H", len(me)) + me)
            # report seen=0 toward a NEW peer incarnation (its seq
            # space restarted) — but do NOT mutate session state yet:
            # an unauthenticated dialer must not be able to reset the
            # dedup cursor or fence off live conns. The reset commits
            # only after the handshake fully validates (below).
            with self._lock:
                stored = self._peer_nonce.get(peer)
                fresh_inst = stored is not None and stored != peer_inst
                last_seen = 0 if fresh_inst \
                    else self._in_seq.get(peer, 0)
            sock.sendall(struct.pack("<Q", last_seen)
                         + bytes([self.mode]) + bytes([self._comp_id]))
            if self.mode == MODE_SECURE:
                import os as _os
                nonce_s = _os.urandom(16)
                offers = bytes([peer_comp, self._comp_id])
                sock.sendall(nonce_s + _auth_proof(
                    self.secret, b"srv",
                    peer_inst + nonce_c, self.instance_nonce + nonce_s,
                    self.name, peer_seen, last_seen, offers))
                proof_c = self._recv_exact(sock, 32)
                want = _auth_proof(
                    self.secret, b"cli",
                    peer_inst + nonce_c, self.instance_nonce + nonce_s,
                    peer, peer_seen, last_seen, offers)
                import hmac as _hmac
                if not _hmac.compare_digest(proof_c, want):
                    raise ConnectionError(f"auth failure from {peer}")
                box = _SecureBox(
                    _derive_key(self.secret, nonce_c, nonce_s),
                    tx_prefix=_PREFIX_SRV, rx_prefix=_PREFIX_CLI)
        except (OSError, ConnectionError, UnicodeDecodeError):
            sock.close()
            return
        self._check_incarnation(peer, peer_inst)   # post-validation
        conn = _Conn(sock, box, peer_inst=peer_inst, comp=comp,
                     stats=self.stats, stats_lock=self._stats_lock,
                     perf=self.perf, flow=self._flow_entry(peer),
                     flow_lock=self._flow_lock)
        # adopt+replay must be one atomic step under the peer lock:
        # published-but-not-yet-replayed is a window where a concurrent
        # send() (which holds only the peer lock) could emit a NEW
        # higher-seq frame first, making the receiver's max-seq dedup
        # discard the later-replayed older frames — silent loss.
        # _connect() already orders it this way; mirror it here.
        with self._plock(peer):
            if not self._adopt(peer, conn, inbound=True):
                return
            self._replay(peer, conn, peer_seen)

    def _replay(self, peer: str, conn: _Conn, peer_seen: int) -> None:
        """Retire entries the peer's handshake already acknowledges
        (a lost final ACK must not wedge flush forever), then resend
        the rest in order (lossless_peer replay)."""
        with self._plock(peer):
            with self._lock:
                q = self._unacked.get(peer)
                while q and q[0][0] <= peer_seen:
                    q.popleft()
                pending = list(q or ())
            try:
                for seq, tid, payload in pending:
                    conn.send_frame(seq, tid, payload)
                    self.perf.inc("replayed")
            except (OSError, ConnectionError):
                pass  # conn died again; next reconnect replays

    def _connect(self, peer: str) -> _Conn:
        """Dial + handshake + replay. Callers hold the peer lock, so
        only one connect per peer runs and replay order is exact."""
        with self._plock(peer):
            if peer in self._blocked:
                raise ConnectionError(f"partitioned from {peer}")
            conn = self._conns.get(peer)
            if conn is not None and conn.alive:
                return conn  # someone beat us to it
            addr = self._addr_of[peer]
            if addr and addr[0] == "unix":
                sock = socket.socket(socket.AF_UNIX,
                                     socket.SOCK_STREAM)
                sock.settimeout(10)
                sock.connect(addr[1])
            else:
                sock = socket.create_connection(tuple(addr),
                                                timeout=10)
            _set_nodelay(sock)
            sock.sendall(BANNER)
            name_b = self.name.encode()
            sock.sendall(struct.pack("<H", len(name_b)) + name_b
                         + self.instance_nonce)
            with self._lock:
                my_seen = self._in_seq.get(peer, 0)
            nonce_c = b""
            if self.mode == MODE_SECURE:
                import os as _os
                nonce_c = _os.urandom(16)
            sock.sendall(struct.pack("<Q", my_seen)
                         + bytes([self.mode]) + bytes([self._comp_id])
                         + nonce_c)
            if self._recv_exact(sock, len(BANNER)) != BANNER:
                sock.close()
                raise ConnectionError(f"bad banner from {peer}")
            peer_inst = self._recv_exact(sock, 8)
            # verify WHO answered: addresses are ephemeral localhost
            # ports, and the OS can hand a dead daemon's port to the
            # next daemon that binds — a ping meant for the corpse
            # would then be cheerfully ponged by an unrelated live
            # daemon, keeping the dead peer "alive" forever and
            # stalling failure detection (ref: ProtocolV2 peer
            # entity/addr validation aborting mismatched connections)
            anlen = struct.unpack("<H", self._recv_exact(sock, 2))[0]
            actual = self._recv_exact(sock, anlen).decode()
            if actual != peer:
                sock.close()
                raise ConnectionError(
                    f"dialed {peer} but reached {actual} "
                    f"(stale address / reused port)")
            peer_seen = struct.unpack("<Q",
                                      self._recv_exact(sock, 8))[0]
            peer_mode = self._recv_exact(sock, 1)[0]
            if peer_mode != self.mode:
                sock.close()
                raise ConnectionError(
                    f"mode mismatch with {peer}: "
                    f"ours={self.mode} theirs={peer_mode}")
            peer_comp = self._recv_exact(sock, 1)[0]
            comp = self._comp_id if peer_comp == self._comp_id \
                else COMP_NONE
            box = None
            if self.mode == MODE_SECURE:
                nonce_s = self._recv_exact(sock, 16)
                proof_s = self._recv_exact(sock, 32)
                import hmac as _hmac
                offers = bytes([self._comp_id, peer_comp])
                want = _auth_proof(
                    self.secret, b"srv",
                    self.instance_nonce + nonce_c, peer_inst + nonce_s,
                    peer, my_seen, peer_seen, offers)
                if not _hmac.compare_digest(proof_s, want):
                    sock.close()
                    raise ConnectionError(f"auth failure from {peer}")
                sock.sendall(_auth_proof(
                    self.secret, b"cli",
                    self.instance_nonce + nonce_c, peer_inst + nonce_s,
                    self.name, my_seen, peer_seen, offers))
                box = _SecureBox(
                    _derive_key(self.secret, nonce_c, nonce_s),
                    tx_prefix=_PREFIX_CLI, rx_prefix=_PREFIX_SRV)
            self._check_incarnation(peer, peer_inst)  # post-validation
            self.perf.inc("reconnects")
            conn = _Conn(sock, box, peer_inst=peer_inst, comp=comp,
                         stats=self.stats, stats_lock=self._stats_lock,
                         perf=self.perf, flow=self._flow_entry(peer),
                         flow_lock=self._flow_lock)
            if not self._adopt(peer, conn, inbound=False):
                # a crossing dial won (we're the non-designated side):
                # the WINNING connection carries the session now — put
                # our pending frames on it instead of stranding them
                # until some future reconnect
                with self._lock:
                    winner = self._conns.get(peer)
                if winner is None or not winner.alive:
                    raise ConnectionError(
                        f"lost connection race to {peer}")
                self._replay(peer, winner, peer_seen)
                return winner
            self._replay(peer, conn, peer_seen)
            return conn

    def _adopt(self, peer: str, conn: _Conn, inbound: bool) -> bool:
        """Install the connection for `peer`, resolving simultaneous-
        connect races deterministically (ProtocolV2's race-winner
        rule): the LOWER name is the designated dialer. The rule must
        bind BOTH sides — the lower name refuses inbound when it has a
        live conn, AND the higher name yields its own outbound dial to
        a live conn — or crossed dials flip-flop killing each other's
        sockets forever. Returns False if this conn lost."""
        with self._lock:
            old = self._conns.get(peer)
            if (old is not None and old.alive
                    and ((inbound and self.name < peer)
                         or (not inbound and self.name > peer))):
                keep_old = True
            else:
                keep_old = False
                self._conns[peer] = conn
        if keep_old:
            conn.close()
            return False
        if old is not None and old is not conn:
            old.close()
        self._bind_reactor(peer, conn)
        return True

    def _bind_reactor(self, peer: str, conn: _Conn) -> None:
        """Bind the handshaken connection to a reactor (round-robin —
        the AsyncMessenger accept-time worker assignment) and start
        event-driven reads. The socket goes nonblocking here; the
        blocking handshake is over."""
        with self._lock:
            r = self._reactors[self._rr % len(self._reactors)]
            self._rr += 1
        conn.reactor = r
        conn.sock.setblocking(False)

        def _cb(mask: int, peer=peer, conn=conn) -> None:
            self._conn_event(peer, conn, mask)
        r.call(lambda: r.register(conn.sock, selectors.EVENT_READ,
                                  _cb))

    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            got = sock.recv(n - len(buf))
            if not got:
                raise ConnectionError("peer closed")
            buf += got
        return buf

    # -- send ----------------------------------------------------------------

    def add_peer(self, peer: str, addr) -> None:
        self._addr_of[peer] = tuple(addr)

    def set_blocked(self, peers) -> None:
        """Partition injection (ref: src/msg/Messenger.h ms_inject_*
        debug-knob family; socket failures and delays have their own
        knobs: set_inject_socket_failures / set_inject_delay): frames
        to/from these peer NAMES stop flowing — live connections are
        killed, new dials raise, inbound handshakes are refused.
        Queued messages stay unacked and replay on heal, which is
        exactly a real partition's semantics: the network drops
        frames, the lossless session replays them afterwards."""
        with self._lock:
            self._blocked = set(peers)
            dead = [(p, c) for p, c in self._conns.items()
                    if p in self._blocked]
            for p, _ in dead:
                del self._conns[p]
        for _, c in dead:
            c.close()

    def _plock(self, peer: str) -> threading.RLock:
        with self._lock:
            lk = self._peer_locks.get(peer)
            if lk is None:
                lk = self._peer_locks[peer] = threading.RLock()
            return lk

    def send(self, peer: str, msg: Message) -> None:
        """Queue + transmit; survives connection death (replayed on
        the next reconnect). Raises only if the peer is unknown or the
        payload won't encode."""
        if self._stopping:
            # a shut-down messenger models a DEAD process: its
            # lingering threads (a reconcile mid-flight at SIGKILL, a
            # dispatch answering a late ping) must not re-dial out,
            # replay queues, and resurrect the daemon on the wire —
            # that keeps a killed OSD "alive" to its peers and stalls
            # failure detection indefinitely
            raise ConnectionError(f"{self.name}: messenger is shut down")
        e = Encoder()
        msg.encode_payload(e)
        # segment list, not one joined buffer: data blobs the encoder
        # appended by reference (blob_ref) travel pointer-style from
        # here through sendmsg — the unacked queue keeps the same list
        # for replay, so the aliasing contract extends until the ack
        payload = e.segments()
        self.perf.inc("msg_tx")
        # ms_inject_socket_failures (ref: src/msg/Messenger.h debug
        # knob): every Nth send tears the live socket down FIRST, so
        # this message and any unacked predecessors must survive
        # through reconnect + replay under real traffic. The knob is
        # snapshotted under the lock: a concurrent disable (every=0)
        # must not hit the modulo mid-send
        victim = None
        delay_s = 0.0
        with self._lock:
            every = self._inject_every
            if every:
                self._inject_count += 1
                if self._inject_count % every == 0:
                    victim = self._conns.get(peer)
            if self._delay_every:
                self._delay_count += 1
                if self._delay_count % self._delay_every == 0:
                    delay_s = self._inject_rng.uniform(
                        0, self._delay_max_ms) / 1e3
                    self._delay_fired += 1
            ld = self._link_delay.get(peer)
            if ld is not None and not getattr(_TLS, "in_reactor",
                                              False):
                # directed link degrade: base + seeded jitter, drawn
                # under the lock from the SAME injection RNG so a
                # logged thrash seed replays the jitter schedule
                base_ms, jitter_ms = ld
                delay_s += (base_ms + (self._inject_rng.uniform(
                    0, jitter_ms) if jitter_ms else 0.0)) / 1e3
                self._link_delay_fired += 1
        if delay_s:
            import time as _time
            _time.sleep(delay_s)
        if victim is not None and victim.alive:
            self._inject_fired += 1
            victim.close()
        with self._plock(peer):
            with self._lock:
                seq = self._out_seq.get(peer, 0) + 1
                self._out_seq[peer] = seq
                self._unacked.setdefault(peer, deque()).append(
                    (seq, msg.type_id, payload))
                conn = self._conns.get(peer)
                if peer in self._blocked:
                    return   # partitioned: queued, replays on heal
            try:
                if conn is None or not conn.alive:
                    conn = self._connect(peer)
                    # _connect replayed the queue incl. this message
                    return
                conn.send_frame(seq, msg.type_id, payload)
            except (OSError, ConnectionError):
                # connection died mid-send: the message stays unacked
                # and replays on the next send/reconnect. Identity
                # check: a fresh conn adopted meanwhile must survive.
                with self._lock:
                    if conn is not None \
                            and self._conns.get(peer) is conn:
                        del self._conns[peer]

    def set_inject_delay(self, every: int, max_ms: float) -> None:
        """Sleep uniform [0, max_ms] before every Nth transmit (the
        ms_inject_delay_max/_probability debug role); every=0 turns it
        off. Delays happen on the SENDER's dispatch path, exactly
        where the reference's injection sits."""
        if every < 0 or max_ms < 0:
            raise ValueError("every and max_ms must be >= 0")
        with self._lock:
            self._delay_every = int(every)
            self._delay_max_ms = float(max_ms)

    def set_link_delay(self, peer: str, delay_ms: float,
                       jitter_ms: float = 0.0) -> None:
        """Degrade the directed link self→peer: sleep delay_ms plus
        uniform [0, jitter_ms] before every transmit toward `peer`
        (sender dispatch path, same seat as set_inject_delay — but
        per-LINK and every send, not every-Nth process-wide).
        delay_ms <= 0 heals the link. Reactor threads are exempt
        (they must never sleep), so fast-dispatch replies cross
        undelayed — the degrade stays one-way."""
        if delay_ms < 0 or jitter_ms < 0:
            delay_ms, jitter_ms = 0.0, 0.0
        with self._lock:
            if delay_ms <= 0 and jitter_ms <= 0:
                self._link_delay.pop(peer, None)
            else:
                self._link_delay[peer] = (float(delay_ms),
                                          float(jitter_ms))

    def clear_link_delays(self) -> None:
        """Heal every degraded link (thrasher _clear_faults hook)."""
        with self._lock:
            self._link_delay.clear()

    def link_delays(self) -> dict:
        """Active link degrades, {peer: {delay_ms, jitter_ms}}."""
        with self._lock:
            return {p: {"delay_ms": d, "jitter_ms": j}
                    for p, (d, j) in self._link_delay.items()}

    def _flow_entry(self, peer: str) -> dict:
        """The per-peer flow ledger entry (created zeroed). Shared by
        every conn toward `peer` across reconnects."""
        with self._flow_lock:
            f = self._flow.get(peer)
            if f is None:
                f = self._flow[peer] = {
                    "bytes_tx": 0, "frames_tx": 0,
                    "bytes_rx": 0, "frames_rx": 0,
                    "stalls": 0, "stall_time_s": 0.0,
                    "writeq_bytes": 0, "writeq_frames": 0,
                }
            return f

    def flow_dump(self) -> dict:
        """Snapshot of per-peer flow: counters plus LIVE write-queue
        depth for peers with an open conn (the ledger's gauge is only
        as fresh as the last flush; prefer the queue itself)."""
        with self._flow_lock:
            out = {p: dict(f) for p, f in self._flow.items()}
        with self._lock:
            conns = list(self._conns.items())
        for p, c in conns:
            if p in out and c.alive:
                out[p]["writeq_bytes"] = c._wq_bytes
                out[p]["writeq_frames"] = len(c._wq)
        for f in out.values():
            f["stall_time_s"] = round(f["stall_time_s"], 6)
        return out

    def seed_injection(self, seed: int) -> None:
        """Reset the injection RNG and counters to a deterministic
        state: with the same seed and the same send sequence, the
        exact same sends get torn down / delayed by the same amounts —
        what makes a logged thrash seed a real reproducer."""
        import random as _random
        with self._lock:
            self._inject_rng = _random.Random(seed)
            self._inject_count = 0
            self._delay_count = 0

    def set_inject_socket_failures(self, every: int) -> None:
        """Tear the live connection down on every Nth send (the
        reference's ms_inject_socket_failures debug knob); 0 turns
        injection off. Exactly-once delivery must hold regardless —
        the lossless replay + receiver seq dedup absorb the chaos."""
        if every < 0:
            raise ValueError("every must be >= 0")
        with self._lock:
            self._inject_every = int(every)

    def flush(self, peer: str, timeout: float = 10.0) -> bool:
        """Block until the peer acked everything (or timeout). The
        sender-side barrier tests use; returns False on timeout."""
        import time
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            with self._lock:
                if not self._unacked.get(peer):
                    return True
                conn = self._conns.get(peer)
            if conn is None or not conn.alive:
                try:
                    self._connect(peer)
                except (OSError, ConnectionError, KeyError):
                    pass
            time.sleep(0.01)
        return False

    # -- receive -------------------------------------------------------------

    def _conn_event(self, peer: str, conn: _Conn, mask: int) -> None:
        """Reactor event entry for one connection. Read side drains
        the socket and parses every complete frame (the _read_loop
        body, event-driven); write side resumes the queued flush."""
        try:
            if mask & selectors.EVENT_READ:
                self._conn_read(peer, conn)
            if mask & selectors.EVENT_WRITE and conn.alive:
                conn._on_writable()
            if not conn.alive:
                raise ConnectionError("connection closed")
        except (OSError, ConnectionError, ValueError):
            self._reactor_reap(peer, conn)

    def _conn_read(self, peer: str, conn: _Conn) -> None:
        # drain with a per-event byte budget: one hot connection must
        # not starve the rest of this reactor (epoll is level-
        # triggered, the remainder fires on the next loop)
        budget = 1 << 20
        while budget > 0 and conn.alive:
            try:
                chunk = conn.sock.recv(1 << 18)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                raise ConnectionError("recv failed")
            if not chunk:
                raise ConnectionError("peer closed")
            budget -= len(chunk)
            rx = conn._rx
            rx += chunk
            pos = 0
            n = len(rx)
            tail = 4 if conn.box is None else 0
            while n - pos >= 4:
                (blen,) = struct.unpack_from("<I", rx, pos)
                floor = 10 if conn.box is None \
                    else 10 + _NONCE + _GCM_TAG
                if blen < floor or blen > (1 << 26):
                    raise ConnectionError(f"bad frame length {blen}")
                if n - pos < 4 + blen + tail:
                    break
                raw_len = bytes(rx[pos:pos + 4])
                body = bytes(rx[pos + 4:pos + 4 + blen])
                crc = None
                if tail:
                    (crc,) = struct.unpack_from("<I", rx,
                                                pos + 4 + blen)
                pos += 4 + blen + tail
                self._on_frame(peer, conn, raw_len, body, crc)
            if pos:
                del rx[:pos]

    def _on_frame(self, peer: str, conn: _Conn, raw_len: bytes,
                  body: bytes, crc: int | None) -> None:
        """One complete wire frame: verify, dedup, ack, dispatch —
        bit-for-bit the blocking read loop's semantics. Raises
        ConnectionError to kill the session (corruption, stale
        incarnation), exactly as before."""
        blen = len(body)
        if conn.box is None:
            t0 = _time_mod.perf_counter()
            if _crc_iov([raw_len, body]) != crc:
                # ProtocolV2 crc mode: corrupt frame kills the
                # session; replay redelivers after reconnect
                raise ConnectionError("frame crc mismatch")
            self.perf.tinc("crc_time",
                           _time_mod.perf_counter() - t0)
            self.perf.inc_many((("frames_rx", 1),
                                ("bytes_rx", 8 + blen)))
            rx_wire = 8 + blen
        else:
            # secure mode: the GCM tag is the integrity check
            # (and the length header is bound in as AAD)
            t0 = _time_mod.perf_counter()
            body = conn.box.open(body, raw_len)
            self.perf.tinc("open_time",
                           _time_mod.perf_counter() - t0)
            self.perf.inc_many((("frames_rx", 1),
                                ("bytes_rx", 4 + blen)))
            rx_wire = 4 + blen
        if conn.flow is not None:
            with conn.flow_lock:
                conn.flow["frames_rx"] += 1
                conn.flow["bytes_rx"] += rx_wire
        seq, tid = struct.unpack_from("<QH", body)
        # zero-copy view over the payload (Decoder accepts a
        # memoryview; blob fields copy out only what they keep)
        payload = memoryview(body)[10:]
        if tid & _COMP_FLAG:
            import zlib
            try:
                o = zlib.decompressobj()
                payload = o.decompress(payload, _DECOMP_MAX)
                if o.unconsumed_tail:
                    raise ConnectionError(
                        "decompressed frame exceeds cap")
                if not o.eof or o.unused_data:
                    # a TRUNCATED stream decompresses without
                    # error — delivering the partial payload
                    # would ack-and-lose the message
                    raise ConnectionError(
                        "compressed frame truncated")
            except zlib.error:
                # garbled compressed body: kill the session
                # exactly like a crc mismatch; replay heals
                raise ConnectionError(
                    "compressed frame corrupt")
            tid &= _COMP_FLAG - 1
            with self._stats_lock:
                self.stats["rx_compressed"] = \
                    self.stats.get("rx_compressed", 0) + 1
            self.perf.inc("rx_compressed")
        # incarnation fencing: a conn authenticated against a
        # peer incarnation that is no longer current must not
        # touch session state — a dying incarnation's buffered
        # frames arriving AFTER the new one's handshake reset
        # would re-poison in_seq with stale high seqs (black-
        # holing the new peer) or retire fresh unacked via old
        # ACKs. Kill the stale conn instead.
        with self._lock:
            cur = self._peer_nonce.get(peer)
        if cur is not None and conn.peer_inst != cur:
            raise ConnectionError(
                "frame from a stale peer incarnation")
        if tid == ACK_TYPE:
            if len(payload) != 8:
                raise ConnectionError("malformed ACK frame")
            (acked,) = struct.unpack("<Q", payload)
            self.perf.inc("acks_rx")
            with self._lock:
                q = self._unacked.get(peer)
                while q and q[0][0] <= acked:
                    q.popleft()
            return
        deliver = False
        with self._lock:
            if seq > self._in_seq.get(peer, 0):
                self._in_seq[peer] = seq
                deliver = True  # else: replayed dup, drop
            ack_seq = self._in_seq.get(peer, 0)
        if not deliver:
            self.perf.inc("dup_rx")
        # coalesced cumulative ack: every ACK_BATCH frames
        # inline, the rest via the ~2ms flusher — replies
        # never wait on acks (they only retire the sender's
        # replay queue), so the delay costs nothing while
        # cutting the rpc pattern's frame count by a third
        if ack_seq - conn.acked_out >= ACK_BATCH:
            conn.acked_out = max(conn.acked_out, ack_seq)
            try:
                conn.send_frame(0, ACK_TYPE,
                                struct.pack("<Q", ack_seq))
            except (OSError, ConnectionError):
                pass
        else:
            self._ack_event.set()
        if deliver:
            self.perf.inc("msg_rx")
            cls = _MSG_TYPES.get(tid)
            ent = self._handlers.get(tid)
            if cls is not None and ent is not None:
                fn, fast = ent
                if not fast:
                    # queued dispatch: decode + run on the dispatch
                    # thread so a blocking fold never stalls this
                    # reactor (replies keep draining meanwhile)
                    self._dispatch_q.put((fn, peer, cls, payload))
                    return
                try:
                    fn(peer, cls.decode_payload(Decoder(payload)))
                except Exception as e:  # poison message: the
                    # frame was crc-valid and is already acked;
                    # contain the blast radius to this message
                    # (fast dispatch must not kill the session)
                    from ..utils.log import g_log
                    g_log.dout("msgr", 0,
                               f"dispatch error from {peer} "
                               f"type={tid:#x} seq={seq}: {e!r}")

    def _reactor_reap(self, peer: str, conn: _Conn) -> None:
        """Reactor-side teardown: unregister + close the fd HERE (the
        only thread that may — a foreign close would race the fd
        number back into the selector) and drop the session's claim
        on this conn."""
        conn.alive = False
        with conn.wlock:
            conn._wcond.notify_all()
        conn._reactor_close()
        with self._lock:
            if self._conns.get(peer) is conn:
                del self._conns[peer]

    def _ack_loop(self) -> None:
        """Flush owed cumulative acks ~2ms after a burst: the sender's
        replay queue retires promptly even when the inline every-Nth
        ack didn't fire (a lone frame, a stream that went quiet)."""
        import time as _time
        while not self._stopping:
            if not self._ack_event.wait(timeout=0.5):
                continue
            self._ack_event.clear()
            _time.sleep(0.02)           # let the burst coalesce
            with self._lock:
                conns = list(self._conns.items())
                seqs = {p: self._in_seq.get(p, 0) for p, _ in conns}
            for peer, conn in conns:
                seq = seqs[peer]
                if conn.alive and seq > conn.acked_out:
                    conn.acked_out = max(conn.acked_out, seq)
                    try:
                        conn.send_frame(0, ACK_TYPE,
                                        struct.pack("<Q", seq))
                    except (OSError, ConnectionError):
                        pass

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._stopping = True
        self._ack_event.set()   # unblock the flusher so it can exit
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            # wake peers + blocked senders now; the fd itself closes
            # with the reactor (it owns every registered socket)
            c.alive = False
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            with c.wlock:
                c._wcond.notify_all()
        for r in self._reactors:
            r.stop()
        for r in self._reactors:
            r.join(timeout=2.0)
        for c in conns:
            if c.reactor is None:
                c._close_fd()
        try:
            self._listener.close()   # reactors are gone: direct close
        except OSError:              # is race-free now (usually a
            pass                     # no-op — reactor 0 owned it)
        if self._uds_path is not None:
            import os as _os
            try:
                _os.unlink(self._uds_path)
            except OSError:
                pass
