"""Seeded op-stream generation — the replay contract (r20).

One profile + one integer seed -> one op stream, generated entirely
up front from a dedicated `random.Random` keyed on (seed, tenant
name). Nothing execution-dependent feeds the generator (no wall
clock, no ack state, no thread timing), so the committed artifact's
`config.seed` + `profiles` block reproduces every tenant's stream
BIT-EXACTLY — `digest()` pins it, `--repro` checks it (the thrasher's
dedicated-stream discipline applied to traffic).

Arrival times come from a thinned non-homogeneous Poisson process:
candidates are drawn at the profile's peak rate, then accepted with
probability scale(t)/peak — which handles burst phases whose off
scale is 0 without the naive rate-inversion hang, and keeps the
draw count (hence the RNG stream) a pure function of the seed.
"""

from __future__ import annotations

import hashlib
import random
from typing import NamedTuple

from .profiles import TenantProfile


class Op(NamedTuple):
    """One generated op. `obj` is the tenant-namespace object index
    (the engine maps it to `wl-<tenant>-<obj>`); offset/size are
    bytes. kind is read | write_at | append | write_full."""

    t: float            # seconds from stream start
    kind: str
    obj: int
    offset: int
    size: int


_WRITE_KIND = {"overwrite": "write_at", "append": "append",
               "full": "write_full"}


class OpStream:
    """Deterministic op stream for one tenant profile."""

    def __init__(self, profile: TenantProfile, seed: int):
        self.profile = profile
        self.seed = int(seed)
        # string-seeded Random is stable across processes and runs
        # (unlike hash()-derived seeds under PYTHONHASHSEED); the
        # tenant name keys the stream so tenants never share draws
        self._rng_key = f"workload/{self.seed}/{profile.name}"

    def generate(self, duration_s: float) -> list[Op]:
        p = self.profile
        rng = random.Random(self._rng_key)
        peak_rate = p.iops * p.max_scale()
        max_scale = p.max_scale()
        lo, hi = p.op_size if isinstance(p.op_size, tuple) \
            else (int(p.op_size), int(p.op_size))
        ops: list[Op] = []
        t = 0.0
        while True:
            t += rng.expovariate(peak_rate)
            if t >= duration_s:
                break
            # thinning: accept at the phase program's local scale
            if rng.random() * max_scale > p.scale_at(t):
                continue
            is_read = rng.random() < p.read_fraction
            if p.hotspot_fraction and rng.random() \
                    < p.hotspot_fraction:
                obj = rng.randrange(min(p.hotspot_objects, p.objects))
            else:
                obj = rng.randrange(p.objects)
            size = rng.randint(lo, hi)
            if is_read:
                ops.append(Op(t, "read", obj, 0, p.object_size))
            elif p.write_mode == "overwrite":
                off = rng.randrange(p.object_size - size + 1)
                ops.append(Op(t, "write_at", obj, off, size))
            elif p.write_mode == "append":
                ops.append(Op(t, "append", obj, 0, size))
            else:           # full: whole-object streaming rewrite
                ops.append(Op(t, "write_full", obj, 0,
                              p.object_size))
        return ops

    @staticmethod
    def digest(ops: list[Op]) -> str:
        """Canonical sha256 over the stream — the bit-exact replay
        pin committed in the artifact's `streams` block. Times are
        fixed to nanosecond text so float repr drift can't fork the
        hex between Python builds."""
        h = hashlib.sha256()
        for op in ops:
            h.update(f"{op.t:.9f}|{op.kind}|{op.obj}|{op.offset}|"
                     f"{op.size}\n".encode())
        return h.hexdigest()

    @staticmethod
    def routed_counts(ops: list[Op]) -> dict:
        """Per-kind op counts — the block-path routing decision
        summary the artifact commits per tenant."""
        out = {"read": 0, "write_at": 0, "append": 0,
               "write_full": 0}
        for op in ops:
            out[op.kind] += 1
        return out


def payload_for(profile: TenantProfile, seed: int) -> bytes:
    """One deterministic max-op-size byte buffer per tenant (sliced
    per op by the engine): payload bytes ride the same replay
    contract as the op metadata without hashing megabytes per op."""
    rng = random.Random(f"workload-payload/{int(seed)}/"
                        f"{profile.name}")
    hi = profile.op_size[1] if isinstance(profile.op_size, tuple) \
        else int(profile.op_size)
    n = max(hi, profile.object_size)
    return rng.randbytes(n)
