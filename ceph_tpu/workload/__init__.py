"""Multi-tenant workload engine (r20).

Declarative, seed-deterministic tenant traffic profiles driving the
block path end-to-end: `profiles` is the JSON/dict grammar (op-size
mix, read/write ratio, temporal phases, QoS class), `streams` turns a
profile + seed into a replayable op stream with a bit-exact digest,
and `engine` executes N tenants concurrently against a live
cephx+secure cluster — small overwrites through the r16
write_at/append fast path, streaming writes through full stripes —
while feeding per-tenant latency into the r18 telemetry plane and
reading back the r20 per-tenant mClock throttle attribution.
"""

from .engine import WorkloadEngine, percentiles
from .profiles import (BUILTIN_PROFILES, Phase, TenantProfile,
                       builtin_mix, parse_profiles)
from .streams import Op, OpStream

__all__ = ["TenantProfile", "Phase", "parse_profiles",
           "builtin_mix", "BUILTIN_PROFILES", "Op", "OpStream",
           "WorkloadEngine", "percentiles"]
