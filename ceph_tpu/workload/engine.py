"""Workload engine — N tenants driving the block path live (r20).

Executes pre-generated per-tenant op streams (streams.py) against a
live cluster, one wire client per cephx tenant entity, one pacing
thread per tenant. The routing contract from the profile grammar:
small overwrites go through `write_at` (the r16 parity-delta RMW
path), log-style writes through `append` (the no-preread tail path),
streaming writes through whole-object `write` (full-stripe encode).

Mid-run faults are the CALLER's job (kill_osd from the bench/test,
the thrasher menu from tools/thrash.py) — the engine just keeps
pacing, counts errors per tenant instead of dying, and timestamps
every completion so latency splits around a fault are computable
after the fact.

Per-tenant attribution read-back:
  - `ingest_clients(tagg)` ships each tenant's client-observed
    latency histogram into the r18 TelemetryAggregator under its
    tenant label (the feed tenant-qualified SLO rules evaluate on);
  - `fold_tenant_mclock(cluster)` folds every live OSD's sched_dump
    `tenant:*` rows into per-entity grant/queue/THROTTLE totals (the
    r20 limit-bound attribution — which tenant mClock is holding
    back, not just who is slow).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .profiles import TenantProfile
from .streams import Op, OpStream, payload_for


def percentiles(lat: list[float]) -> dict:
    """Same shape as tools/rados_bench.py:percentiles (kept local so
    the package never imports from tools/)."""
    if not lat:
        return {}
    a = np.sort(np.asarray(lat))
    pick = lambda q: float(a[min(len(a) - 1, int(q * len(a)))])  # noqa: E731
    return {"p50_ms": round(pick(0.50) * 1e3, 3),
            "p95_ms": round(pick(0.95) * 1e3, 3),
            "p99_ms": round(pick(0.99) * 1e3, 3),
            "p999_ms": round(pick(0.999) * 1e3, 3),
            "max_ms": round(float(a[-1]) * 1e3, 3)}


class _TenantState:
    __slots__ = ("profile", "entity", "client", "ops", "payload",
                 "lat", "stamps", "errors", "digest", "routed")

    def __init__(self, profile: TenantProfile):
        self.profile = profile
        self.entity = profile.entity
        self.client = None
        self.ops: list[Op] = []
        self.payload = b""
        self.lat: list[float] = []
        self.stamps: list[float] = []
        self.errors = 0
        self.digest = ""
        self.routed: dict = {}


# op failures during an injected fault window count, not raise — the
# same tolerance set the benches use around --recovery-kill
_FAULT_ERRORS = (ConnectionError, OSError, RuntimeError, KeyError)


class WorkloadEngine:
    """Drive tenant profiles against a live StandaloneCluster."""

    def __init__(self, cluster, profiles: list[TenantProfile],
                 seed: int = 0, duration_s: float = 5.0):
        if not profiles:
            raise ValueError("workload engine needs >= 1 profile")
        self.c = cluster
        self.profiles = list(profiles)
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.tenants: dict[str, _TenantState] = {}
        self._t0 = 0.0
        self.elapsed = 0.0

    # -- declarative -> cluster state -----------------------------------------

    def mclock_tenant_table(self) -> str:
        """osd_mclock_scheduler_tenant_profiles value for every
        profile that pins a QoS class ('' when none do)."""
        return ";".join(f"{p.entity}={p.mclock}"
                        for p in self.profiles if p.mclock)

    def slo_rule_text(self) -> str:
        """Tenant-qualified mgr_slo_rules text: each profile's rule
        fragment suffixed with its `[tenant=...]` qualifier (the r20
        grammar extension)."""
        return ";".join(f"{p.slo} [tenant={p.entity}]"
                        for p in self.profiles if p.slo)

    def setup(self) -> None:
        """Create one cephx entity + wire client per tenant, commit
        the mClock tenant table, stage each tenant's object
        namespace, and generate (+digest) every op stream."""
        table = self.mclock_tenant_table()
        admin = self.c.client()
        if table:
            admin.config_set("osd_mclock_scheduler_tenant_profiles",
                             table)
        for p in self.profiles:
            st = _TenantState(p)
            if getattr(self.c, "key_server", None) is not None:
                sec = self.c.create_entity(
                    p.entity, caps={"mon": "allow r",
                                    "osd": "allow rwx"})
                st.client = self.c.client(entity=p.entity,
                                          secret=sec)
            else:
                st.client = self.c.client()
                st.entity = st.client.msgr.name
            st.payload = payload_for(p, self.seed)
            # stage the overwrite/read namespace at full object size
            # (append streams grow their own `wls-` objects from
            # empty, so every append lands on the no-preread path)
            staged = st.payload[:p.object_size]
            st.client.write({self._obj(p, i): staged
                             for i in range(p.objects)})
            stream = OpStream(p, self.seed)
            st.ops = stream.generate(self.duration_s)
            st.digest = OpStream.digest(st.ops)
            st.routed = OpStream.routed_counts(st.ops)
            self.tenants[p.name] = st

    @staticmethod
    def _obj(p: TenantProfile, i: int) -> str:
        return f"wl-{p.name}-{i}"

    @staticmethod
    def _stream_obj(p: TenantProfile, i: int) -> str:
        return f"wls-{p.name}-{i}"

    # -- execution ------------------------------------------------------------

    def _run_tenant(self, st: _TenantState, start: threading.Event):
        p, cl = st.profile, st.client
        start.wait()
        t0 = self._t0
        for op in st.ops:
            delay = t0 + op.t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            ts = time.perf_counter()
            try:
                if op.kind == "read":
                    cl.read(self._obj(p, op.obj))
                elif op.kind == "write_at":
                    cl.write_at(self._obj(p, op.obj), op.offset,
                                st.payload[:op.size])
                elif op.kind == "append":
                    cl.append(self._stream_obj(p, op.obj),
                              st.payload[:op.size])
                else:       # write_full: full-stripe streaming write
                    cl.write({self._obj(p, op.obj):
                              st.payload[:p.object_size]})
            except _FAULT_ERRORS:
                # op raced a fault window (dead primary, map lag):
                # real clients retry; the engine counts and paces on
                st.errors += 1
                continue
            done = time.perf_counter()
            st.lat.append(done - ts)
            st.stamps.append(done)

    def run(self, tick=None, tick_interval: float = 0.5) -> None:
        """Run every tenant to stream completion. `tick()` (optional)
        fires every `tick_interval` seconds on its own thread while
        tenants run — the bench/test hook that ships per-tenant
        client histograms into telemetry at interval cadence."""
        start = threading.Event()
        threads = [threading.Thread(target=self._run_tenant,
                                    args=(st, start), daemon=True)
                   for st in self.tenants.values()]
        for th in threads:
            th.start()
        stop = threading.Event()
        ticker = None
        if tick is not None:
            def _tick_loop():
                while not stop.wait(tick_interval):
                    try:
                        tick()
                    except Exception:   # noqa: BLE001 — a tick racing
                        pass            # a dying daemon never kills IO
            ticker = threading.Thread(target=_tick_loop, daemon=True)
            ticker.start()
        self._t0 = time.perf_counter()
        start.set()
        for th in threads:
            th.join()
        self.elapsed = time.perf_counter() - self._t0
        stop.set()
        if ticker is not None:
            ticker.join(timeout=2.0)
        if tick is not None:
            try:
                tick()      # one closing tick so short runs still
            except Exception:   # noqa: BLE001 — see above
                pass            # land their final interval point

    # -- attribution read-back ------------------------------------------------

    def ingest_clients(self, tagg) -> None:
        """Ship every tenant's client-observed latency histogram into
        the TelemetryAggregator under its tenant label — the feed the
        `[tenant=...]`-qualified SLO rules evaluate against."""
        for st in self.tenants.values():
            tagg.ingest_client(st.client.msgr.name,
                               st.client.perf.dump(),
                               tenant=st.entity)

    @staticmethod
    def fold_tenant_mclock(cluster) -> dict:
        """Per-entity mClock occupancy summed over live daemons'
        sched_dump `tenant:*` rows: queued / served / served_cost /
        THROTTLED (limit-bound dequeue skips) + the committed
        profile. The same fold MgrReportAggregator.tenants() serves
        over the report pipe — read directly here so a bench isn't
        gated on report cadence."""
        out: dict[str, dict] = {}
        for d in cluster.osds.values():
            if d._stop.is_set():
                continue
            try:
                dump = d.sched_dump()
            except Exception:   # noqa: BLE001 — dying daemon drops out
                continue
            for cname, row in dump.items():
                if not cname.startswith("tenant:"):
                    continue
                ent = cname[len("tenant:"):]
                cur = out.setdefault(ent, {
                    "queued": 0, "served": 0, "served_cost": 0.0,
                    "throttled": 0, "profile": row.get("profile")})
                cur["queued"] += row.get("queued", 0)
                cur["served"] += row.get("served", 0)
                cur["served_cost"] += row.get("served_cost", 0.0)
                cur["throttled"] += row.get("throttled", 0)
                if row.get("profile"):
                    cur["profile"] = row["profile"]
        for row in out.values():
            row["served_cost"] = round(row["served_cost"], 3)
        return out

    def results(self, killed_at: float | None = None) -> dict:
        """Per-tenant outcome block: routed op counts, completion/
        error totals, latency percentiles — split pre/post a fault
        timestamp when one is given."""
        out = {}
        for st in self.tenants.values():
            row = {
                "entity": st.entity,
                "klass": st.profile.klass,
                "stream_ops": len(st.ops),
                "ops": len(st.lat),
                "errors": st.errors,
                "routed": st.routed,
                "digest": st.digest,
                **percentiles(st.lat),
            }
            if killed_at is not None:
                pre = [v for t, v in zip(st.stamps, st.lat)
                       if t < killed_at]
                post = [v for t, v in zip(st.stamps, st.lat)
                        if t >= killed_at]
                row["pre_kill"] = percentiles(pre)
                row["post_kill"] = percentiles(post)
            out[st.profile.name] = row
        return out
