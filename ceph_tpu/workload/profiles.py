"""Tenant traffic profiles — the declarative workload grammar (r20).

A profile is a plain dict (JSON-serializable, committed verbatim into
the bench artifact) describing ONE tenant's traffic: op-size mix,
read/write ratio, write routing mode, object namespace + hotspot
skew, a temporal phase program (diurnal ramps, bursty duty cycles),
and the QoS knobs the run commits for it — an mClock
reservation/weight/limit profile and a per-tenant SLO rule fragment.

The grammar is deliberately closed-form: everything the op-stream
generator reads is in the profile + one integer seed, so a committed
artifact's `profiles` block + `config.seed` replays the exact op
streams (streams.OpStream digests pin this bit-exactly).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

# write routing modes — the block-path decision the engine makes per
# op (ref: the r16 partial-stripe work; ISSUE r20 item 1):
#   overwrite: small in-place patches via write_at (parity-delta RMW)
#   append:    tail appends via the rados append op (no-preread path)
#   full:      whole-object rewrites (full-stripe encode — streaming)
WRITE_MODES = ("overwrite", "append", "full")

PHASE_KINDS = ("steady", "ramp", "burst")


@dataclass
class Phase:
    """One segment of a tenant's temporal program.

    kind=steady: constant `scale` x base iops.
    kind=ramp:   linear `from_scale` -> `to_scale` over the segment —
                 the diurnal ramp primitive (chain two for a day).
    kind=burst:  square wave, `on_scale` for duty*period then
                 `off_scale` — the bursty-neighbor primitive.
    duration_s=0 means "the rest of the run"; the program cycles if
    it ends before the run does.
    """

    kind: str = "steady"
    duration_s: float = 0.0
    scale: float = 1.0          # steady
    from_scale: float = 1.0     # ramp
    to_scale: float = 1.0       # ramp
    period_s: float = 1.0       # burst
    duty: float = 0.5           # burst: fraction of period at on_scale
    on_scale: float = 1.0       # burst
    off_scale: float = 0.0      # burst

    def __post_init__(self):
        if self.kind not in PHASE_KINDS:
            raise ValueError(f"bad phase kind {self.kind!r} "
                             f"(want one of {PHASE_KINDS})")
        if self.duration_s < 0:
            raise ValueError("phase duration_s must be >= 0")
        if self.kind == "burst":
            if self.period_s <= 0 or not (0.0 < self.duty <= 1.0):
                raise ValueError("burst phase needs period_s > 0 and "
                                 "0 < duty <= 1")
        for v in (self.scale, self.from_scale, self.to_scale,
                  self.on_scale, self.off_scale):
            if v < 0:
                raise ValueError("phase scales must be >= 0")

    def scale_at(self, t: float) -> float:
        """Rate multiplier `t` seconds into THIS phase."""
        if self.kind == "steady":
            return self.scale
        if self.kind == "ramp":
            if self.duration_s <= 0:
                return self.to_scale
            f = min(1.0, max(0.0, t / self.duration_s))
            return self.from_scale + f * (self.to_scale
                                          - self.from_scale)
        # burst
        return self.on_scale if (t % self.period_s) \
            < self.duty * self.period_s else self.off_scale

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "duration_s": self.duration_s}
        if self.kind == "steady":
            d["scale"] = self.scale
        elif self.kind == "ramp":
            d["from_scale"] = self.from_scale
            d["to_scale"] = self.to_scale
        else:
            d.update(period_s=self.period_s, duty=self.duty,
                     on_scale=self.on_scale, off_scale=self.off_scale)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Phase":
        known = {f for f in cls.__dataclass_fields__}   # noqa: C416
        bad = set(d) - known
        if bad:
            raise ValueError(f"unknown phase keys {sorted(bad)}")
        return cls(**d)


@dataclass
class TenantProfile:
    """One tenant's declarative traffic contract.

    `name` becomes the cephx entity `client.<name>` — the identity
    every OSD's mClock keys its `tenant:<entity>` class on, the
    telemetry plane keys its latency ring on, and the SLO qualifier
    names. `mclock` ('res,wgt,lim', ops/s-space) is committed into
    osd_mclock_scheduler_tenant_profiles; `slo` is a
    client_observed_* rule fragment the engine suffixes with
    `[tenant=client.<name>]`.
    """

    name: str
    klass: str = "interactive"       # free-form label in the artifact
    iops: float = 20.0               # base op rate (phases scale it)
    read_fraction: float = 0.5
    op_size: int | tuple[int, int] = 1024       # bytes (or [lo, hi])
    write_mode: str = "overwrite"
    objects: int = 8                 # namespace width
    object_size: int = 8192          # staged size (overwrite bounds)
    hotspot_fraction: float = 0.0    # ops drawn to the hot set
    hotspot_objects: int = 1         # hot-set width
    phases: list[Phase] = field(default_factory=lambda: [Phase()])
    mclock: str | None = None        # 'res,wgt,lim' or None (default)
    slo: str | None = None           # e.g. 'client_observed_p99 < ...'

    def __post_init__(self):
        if not self.name or not str(self.name).replace("-", "") \
                .replace("_", "").replace(".", "").isalnum():
            raise ValueError(f"bad tenant name {self.name!r}")
        if self.write_mode not in WRITE_MODES:
            raise ValueError(f"bad write_mode {self.write_mode!r} "
                             f"(want one of {WRITE_MODES})")
        if isinstance(self.op_size, (list, tuple)):
            lo, hi = (int(v) for v in self.op_size)
            if not (0 < lo <= hi):
                raise ValueError(f"bad op_size range {self.op_size!r}")
            self.op_size = (lo, hi)
        elif int(self.op_size) <= 0:
            raise ValueError("op_size must be > 0")
        if not (0.0 <= self.read_fraction <= 1.0):
            raise ValueError("read_fraction must be in [0, 1]")
        if self.iops <= 0:
            raise ValueError("iops must be > 0")
        if self.objects < 1 or self.object_size < 1:
            raise ValueError("objects/object_size must be >= 1")
        if not (0.0 <= self.hotspot_fraction <= 1.0):
            raise ValueError("hotspot_fraction must be in [0, 1]")
        if self.hotspot_objects < 1:
            raise ValueError("hotspot_objects must be >= 1")
        if not self.phases:
            raise ValueError("profile needs at least one phase")
        max_sz = self.op_size[1] if isinstance(self.op_size, tuple) \
            else int(self.op_size)
        if self.write_mode == "overwrite" and max_sz \
                > self.object_size:
            raise ValueError(f"overwrite op_size {max_sz} exceeds "
                             f"object_size {self.object_size}")
        if self.mclock is not None:
            # fail at parse time, not when the table hits the OSDs
            from ..osd.scheduler import parse_profile
            parse_profile(self.mclock)

    @property
    def entity(self) -> str:
        return f"client.{self.name}"

    def max_scale(self) -> float:
        """Peak phase multiplier — the thinning envelope the stream
        generator draws candidate arrivals at."""
        peak = 0.0
        for ph in self.phases:
            if ph.kind == "steady":
                peak = max(peak, ph.scale)
            elif ph.kind == "ramp":
                peak = max(peak, ph.from_scale, ph.to_scale)
            else:
                peak = max(peak, ph.on_scale, ph.off_scale)
        return max(peak, 1e-9)

    def scale_at(self, t: float) -> float:
        """Rate multiplier `t` seconds into the run: walk the phase
        program, cycling when it is shorter than the run."""
        total = sum(ph.duration_s for ph in self.phases)
        rest = [ph for ph in self.phases if ph.duration_s <= 0]
        if total > 0 and not rest:
            t = t % total
        for ph in self.phases:
            if ph.duration_s <= 0:     # "rest of the run"
                return ph.scale_at(t)
            if t < ph.duration_s:
                return ph.scale_at(t)
            t -= ph.duration_s
        return self.phases[-1].scale_at(t)

    def to_dict(self) -> dict:
        return {
            "name": self.name, "klass": self.klass,
            "iops": self.iops, "read_fraction": self.read_fraction,
            "op_size": list(self.op_size)
            if isinstance(self.op_size, tuple) else self.op_size,
            "write_mode": self.write_mode,
            "objects": self.objects,
            "object_size": self.object_size,
            "hotspot_fraction": self.hotspot_fraction,
            "hotspot_objects": self.hotspot_objects,
            "phases": [ph.to_dict() for ph in self.phases],
            "mclock": self.mclock, "slo": self.slo,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TenantProfile":
        known = {f for f in cls.__dataclass_fields__}   # noqa: C416
        bad = set(d) - known
        if bad:
            raise ValueError(f"unknown profile keys {sorted(bad)}")
        d = dict(d)
        if "phases" in d:
            d["phases"] = [Phase.from_dict(p) if isinstance(p, dict)
                           else p for p in d["phases"]]
        return cls(**d)


def parse_profiles(spec) -> list[TenantProfile]:
    """JSON text / list-of-dicts -> validated profiles. Duplicate
    tenant names are an error (the entity is the identity key
    everywhere downstream)."""
    if isinstance(spec, str):
        spec = json.loads(spec)
    if isinstance(spec, dict):
        spec = [spec]
    out = [p if isinstance(p, TenantProfile)
           else TenantProfile.from_dict(p) for p in spec]
    names = [p.name for p in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant names in {names}")
    return out


# The committed 4-tenant mix (the WORKLOAD_r20 cast): a latency-
# sensitive interactive tenant on a diurnal ramp, a streaming tenant
# pushing full stripes, a bursty small-op tenant riding the append
# path, and a deliberately misbehaving noisy neighbor — high-rate
# hotspot overwrites under a LOW mClock limit, so the throttle
# attribution (not just its latency) shows who the cluster is
# holding back.
BUILTIN_PROFILES: dict[str, dict] = {
    "interactive": {
        "name": "interactive", "klass": "interactive",
        "iops": 30.0, "read_fraction": 0.7,
        "op_size": [512, 2048], "write_mode": "overwrite",
        "objects": 16, "object_size": 8192,
        "phases": [{"kind": "ramp", "duration_s": 0.0,
                    "from_scale": 0.6, "to_scale": 1.4}],
        "slo": "client_observed_p99 < 2500ms over 60s",
    },
    "streaming": {
        "name": "streaming", "klass": "streaming",
        "iops": 8.0, "read_fraction": 0.25,
        "op_size": 16384, "write_mode": "full",
        "objects": 6, "object_size": 16384,
        "phases": [{"kind": "steady", "scale": 1.0}],
        "slo": "client_observed_p99 < 2500ms over 60s",
    },
    "bursty": {
        "name": "bursty", "klass": "bursty",
        "iops": 25.0, "read_fraction": 0.3,
        "op_size": [256, 1024], "write_mode": "append",
        "objects": 8, "object_size": 4096,
        "phases": [{"kind": "burst", "duration_s": 0.0,
                    "period_s": 1.0, "duty": 0.35,
                    "on_scale": 2.5, "off_scale": 0.2}],
        "slo": "client_observed_p99 < 2500ms over 60s",
    },
    "noisy": {
        "name": "noisy", "klass": "noisy",
        "iops": 220.0, "read_fraction": 0.1,
        "op_size": 512, "write_mode": "overwrite",
        "objects": 8, "object_size": 4096,
        "hotspot_fraction": 0.8, "hotspot_objects": 2,
        "phases": [{"kind": "steady", "scale": 1.0}],
        # the misbehavior contract: demand ~220 ops/s, granted 25 —
        # its tenant class goes limit-bound and the r20 throttle
        # counter attributes the backpressure to IT by name
        "mclock": "5,1,25",
        "slo": "client_observed_p99 < 20ms over 60s",
    },
}


def builtin_mix(names=None) -> list[TenantProfile]:
    """The named builtin profiles (default: all four), validated."""
    names = list(names) if names else list(BUILTIN_PROFILES)
    missing = [n for n in names if n not in BUILTIN_PROFILES]
    if missing:
        raise ValueError(f"unknown builtin profiles {missing} "
                         f"(have {sorted(BUILTIN_PROFILES)})")
    return parse_profiles([BUILTIN_PROFILES[n] for n in names])
