"""Multi-chip sharding: the rebuild's distributed communication backend.

The reference fans EC sub-ops out over OSDs through its AsyncMessenger
(ref: src/msg/async/, ECBackend::handle_sub_write/_reply scatter/gather —
SURVEY.md §2.5, §5 "Distributed communication backend"). TPU-native, that
becomes a device mesh + XLA collectives over ICI:

  axis "dp"    — data parallelism over the object batch (the reference's
                 many-PGs-in-flight axis, P2 in SURVEY.md §2.7);
  axis "shard" — shard placement: the k+m chunks of each stripe live on
                 different devices, like chunks on different OSDs (P1/P3).

Encode scatters parity shards across the "shard" axis (XLA inserts the
scatter from the output sharding); degraded decode gathers surviving
shards over ICI (XLA inserts the all-gather from the survivor indexing).
No hand-written NCCL-style calls — shardings in, collectives out.

Multi-host: the same Meshes span hosts via jax.distributed; ICI carries
the "shard" axis within a pod, DCN carries "dp" across pods.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..gf.numpy_ref import decode_matrix
from ..ops.rs_kernels import DEFAULT_IMPL, apply_matrix


def encode_all_chunks(coder, obj: np.ndarray) -> np.ndarray:
    """(n_chunks, chunk_len) dense stack of every chunk of one object —
    the bridge from a codec's dict-shaped encode() into the sharded
    mesh paths (and their tests)."""
    n = coder.get_chunk_count()
    enc = coder.encode(range(n), obj)
    return np.stack([np.asarray(enc[i]) for i in range(n)])


def default_mesh(devices=None, shard: int = 2) -> Mesh:
    """(dp, shard) mesh over the given (default: all) devices.

    `shard` devices hold disjoint subsets of each stripe's k+m chunks;
    the rest of the devices form the batch-parallel axis. `shard` must
    divide the device count — a silently different topology than the one
    the caller modeled would misplace every shard group.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if shard < 1 or n % shard:
        raise ValueError(
            f"shard axis {shard} does not divide device count {n}; "
            f"pick a divisor (e.g. {[d for d in (1, 2, 4, 8) if n % d == 0]})")
    return Mesh(devices.reshape(n // shard, shard), ("dp", "shard"))


def chunk_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a (batch, n_chunks, L) chunk tensor: batch over dp,
    chunk slots over shard — each device is an 'OSD group' holding its
    slice of every stripe."""
    return NamedSharding(mesh, P("dp", "shard", None))


def data_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp", None, None))


def padded_slots(n_chunks: int, mesh: Mesh) -> int:
    """Chunk-slot count padded up to a multiple of the shard axis so the
    slot axis divides evenly across devices (empty tail slots are zero —
    the analog of unused placement slots, not of real shards)."""
    s = mesh.devices.shape[mesh.axis_names.index("shard")]
    return -(-n_chunks // s) * s


def make_sharded_encoder(matrix: np.ndarray, mesh: Mesh,
                         impl: str = DEFAULT_IMPL):
    """Jitted step: (B, k, L) data -> (B, padded_slots(k+m), L) chunks,
    output scattered over the shard axis (the TPU analog of
    MOSDECSubOpWrite fan-out). Slots >= k+m are zero padding."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    n = matrix.shape[0] + matrix.shape[1]
    pad = padded_slots(n, mesh) - n

    def step(data):
        parity = apply_matrix(matrix, data, impl=impl)
        chunks = jnp.concatenate([data, parity], axis=1)
        if pad:
            chunks = jnp.pad(chunks, ((0, 0), (0, pad), (0, 0)))
        return chunks

    return jax.jit(step, in_shardings=data_sharding(mesh),
                   out_shardings=chunk_sharding(mesh))


def make_sharded_gather_apply(D: np.ndarray, slots: tuple[int, ...],
                              mesh: Mesh, impl: str = DEFAULT_IMPL):
    """Jitted step: sharded (B, n_slots, L) chunks -> (B, rows(D), L).

    Indexing the given shard slots forces an ICI all-gather of exactly
    those chunks (the TPU analog of MOSDECSubOpRead gather), then the
    static GF matrix runs batched on every dp slice. The building block
    for degraded decode, LRC local repair, and any derived linear
    repair (ec.linearize)."""
    D = np.asarray(D, dtype=np.uint8)
    idx = np.asarray(slots, dtype=np.int32)

    def step(chunks):
        return apply_matrix(D, chunks[:, idx, :], impl=impl)

    return jax.jit(step, in_shardings=chunk_sharding(mesh),
                   out_shardings=data_sharding(mesh))


def make_sharded_decoder(matrix: np.ndarray, erasures: tuple[int, ...],
                         survivors: tuple[int, ...], mesh: Mesh,
                         impl: str = DEFAULT_IMPL):
    """Jitted step: sharded (B, n, L) chunks -> (B, E, L) reconstructed
    (degraded read across the mesh; see make_sharded_gather_apply)."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    k = matrix.shape[1]
    D = decode_matrix(matrix, list(erasures), k, list(survivors))
    return make_sharded_gather_apply(D, tuple(survivors), mesh, impl)


def make_sharded_clay_repair(coder, failed_chunk: int,
                             helper_chunks: tuple[int, ...], mesh: Mesh,
                             impl: str = DEFAULT_IMPL):
    """Jitted step: sharded (B, n_slots, L) chunks -> (B, L) rebuilt
    Clay chunk, reading ONLY the helpers' repair-plane sub-chunks (the
    MSR bandwidth win, beta = q^(t-1) of q^t sub-chunks per helper)
    before one static matrix-apply on every dp slice."""
    D, rplanes = coder.repair_plan_matrix(failed_chunk, helper_chunks)
    D = np.asarray(D, dtype=np.uint8)
    nsub = coder.get_sub_chunk_count()
    idx = np.asarray(helper_chunks, dtype=np.int32)
    planes = np.asarray(rplanes, dtype=np.int32)
    d, nrp = len(helper_chunks), len(rplanes)

    def step(chunks):
        B, _, L = chunks.shape
        helpers = chunks[:, idx, :]                    # ICI gather of d
        sub = helpers.reshape(B, d, nsub, L // nsub)
        rp = sub[:, :, planes, :]                      # beta sub-chunks
        stacked = rp.reshape(B, d * nrp, L // nsub)
        out = apply_matrix(D, stacked, impl=impl)      # (B, nsub, L//nsub)
        return out.reshape(B, L)

    return jax.jit(step, in_shardings=chunk_sharding(mesh),
                   out_shardings=NamedSharding(mesh, P("dp", None)))


@functools.lru_cache(maxsize=8)
def _cpu_mesh_devices(n: int):
    devs = jax.devices()
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices, have {len(devs)}")
    return tuple(devs[:n])


def virtual_mesh(n_devices: int, shard: int = 2) -> Mesh:
    """Mesh over the first n devices (virtual CPU devices in tests)."""
    return default_mesh(np.asarray(_cpu_mesh_devices(n_devices)), shard)
