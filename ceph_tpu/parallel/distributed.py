"""Multi-host initialization — the DCN half of the comm backend.

The reference scales across nodes with its AsyncMessenger (ref:
src/msg/async/AsyncMessenger.cc — every OSD/mon process dials peers
over TCP/RDMA; SURVEY.md §5 "Distributed communication backend" maps
that to: ICI collectives inside a pod, DCN + jax.distributed across
hosts). This module owns the process-level wiring:

* `init_process()` — jax.distributed.initialize with explicit
  coordinator/rank/size (the messenger bind+dial step). After it, every
  process sees the GLOBAL device list and Meshes span hosts.
* `host_mesh()` — a ("dp", "shard") mesh laid out so the shard axis
  stays INSIDE each process's local devices (ICI) and dp crosses
  processes (DCN). Shard-group collectives (the per-stripe
  gather/scatter, the hot path) then never leave a host; only the
  batch axis — which needs no communication during encode/decode —
  spans the slow network. This is the layout rule from the scaling
  playbook: put the bandwidth-hungry axis on the fast interconnect.
* `global_batch()` — assemble per-host (B_local, k, L) arrays into one
  global jax.Array over that mesh (jax.make_array_from_process_local
  _data), the moral analog of each OSD contributing its own objects.

Verified by tests/test_distributed.py, which launches REAL multiple
processes (two jax.distributed CPU processes on localhost) and runs
the sharded encoder over the spanning mesh — the many-daemons-one-box
trick (qa/standalone/ceph-helpers.sh) applied to hosts.
"""

from __future__ import annotations

import numpy as np


def init_process(coordinator: str, num_processes: int,
                 process_id: int, local_devices: int | None = None):
    """Join the process group (call once per process, before any other
    jax use). Returns the jax module for convenience."""
    import jax
    if local_devices is not None:
        # CPU hosts: carve N virtual local devices (tests / dev boxes)
        jax.config.update("jax_num_cpu_devices", local_devices)
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax


def host_mesh(shard: int | None = None):
    """Global ("dp", "shard") mesh with shard-axis locality: device
    columns within a row belong to one process, so per-stripe
    collectives ride ICI; rows (dp) cross hosts over DCN."""
    import jax
    from jax.sharding import Mesh

    procs: dict[int, list] = {}
    for d in jax.devices():
        procs.setdefault(d.process_index, []).append(d)
    per_host = {p: len(ds) for p, ds in procs.items()}
    if len(set(per_host.values())) > 1:
        # uneven hosts would contribute uneven dp-row counts, breaking
        # the equal-local-batch contract of global_batch(); TPU pods
        # are homogeneous, so reject loudly instead of silently
        # dropping devices
        raise ValueError(f"heterogeneous hosts {per_host}; host_mesh "
                         f"needs the same device count per process")
    n_local = next(iter(per_host.values()))
    if shard is None:
        shard = n_local
    if shard < 1 or n_local % shard:
        raise ValueError(f"shard={shard} does not divide the "
                         f"{n_local} local devices per host")
    rows = []
    for p in sorted(procs):
        ds = procs[p]
        for i in range(0, n_local, shard):
            rows.append(ds[i:i + shard])
    return Mesh(np.asarray(rows), ("dp", "shard"))


def global_batch(mesh, local: np.ndarray):
    """Per-process (B_local, k, L) uint8 -> one global jax.Array
    sharded (dp-major) over the mesh; B_global = sum of locals."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P("dp", None, None))
    return jax.make_array_from_process_local_data(sharding, local)
