"""TinStore — the persistent, crash-consistent ObjectStore.

A minimal file-backed store behind the exact ObjectStore interface
MemStore implements, so every backend/cluster path runs unchanged on
either (the reference parameterizes one suite over MemStore and
BlueStore the same way; ref: src/test/objectstore/store_test.cc).

Design (the load-bearing slice of the reference's L4, ref:
src/os/bluestore/BlueStore.cc _do_write/_kv_sync_thread WAL discipline,
_verify_csum read-path checksums, BlueStore::fsck; transactional
contract ref: src/os/ObjectStore.h Transaction/queue_transaction):

* WRITE-AHEAD LOG. Every queue_transaction serializes its op list to
  one length-prefixed, crc32c-sealed record and appends it to
  `wal.log` BEFORE any state mutates. A transaction is either wholly
  in the WAL or absent — the atomicity unit is the record. `flush()`
  to the OS happens on every commit (process-kill consistency);
  `o_dsync=True` adds an fsync per commit (machine-crash consistency,
  the reference's bluefs WAL fsync).
* RAM MIRROR. Committed state is applied to an internal MemStore,
  which serves all reads — the disk is the durability plane, RAM the
  serving plane (BlueStore's onode/buffer cache role, taken to the
  limit that fits this framework's test scale).
* CHECKPOINTS. When the WAL exceeds `wal_max_bytes`, the whole state
  is serialized (versioned encoding, per-object crc32c, whole-file
  seal) to `ckpt.tmp` and atomically renamed over `ckpt`; WAL records
  up to the checkpoint seq become dead weight and the log is reset.
  Replay seq-skips anything the checkpoint already covers, so a crash
  between rename and reset double-applies nothing.
* VERIFY-ON-READ. Each object carries its crc32c (native C kernel,
  bit-identical to ceph_crc32c — csum/reference.py parity-pinned);
  read()/getattr-adjacent paths re-checksum the served data and raise
  `TinStoreCorruption` on mismatch (the _verify_csum -EIO analog).
  Mount re-verifies every object loaded from a checkpoint.
* RECOVERY. mount() = load newest valid checkpoint, then replay WAL
  records in seq order, each crc-checked. A torn tail record (the
  crash-mid-append window) is detected and truncated away; a corrupt
  record BEFORE valid ones is real damage and fails fsck loudly.
* FSCK. TinStore.fsck(path) re-reads everything offline and reports
  {objects, bad_objects, wal_records, torn_tail, errors} without
  touching a live instance.

Process-kill semantics for the chaos tests: crash() drops the RAM
mirror and file handles with NO checkpoint (what SIGKILL leaves
behind); remount() recovers purely from disk. SimCluster(store="tin")
routes kill/revive through these, so thrash survival is a measured
property of the WAL, not an axiom of the sim.
"""

from __future__ import annotations

import os
import struct
import threading

import numpy as np

from ..utils.encoding import Decoder, Encoder, EncodingError
from .memstore import MemStore, Transaction, _Object

_REC_MAGIC = 0x544E4952    # "RINT" little-endian: record
_REC_HDR = struct.Struct("<IQI")     # magic, seq, body_len
_CKPT_VERSION = 1


class TinStoreCorruption(IOError):
    """Checksum mismatch on the read path (the -EIO analog)."""


_crc_impl = None


def _crc32c(data) -> int:
    """Whole-buffer crc32c, raw-register convention (seed 0xFFFFFFFF,
    no final inversion) — native C fast path, pure-python fallback."""
    global _crc_impl
    if _crc_impl is None:
        try:
            from ..native import lib
            L = lib()

            def _crc_impl(b, _L=L):
                return int(_L.ec_crc32c(0xFFFFFFFF, b, len(b)))
        except Exception:          # no toolchain: correctness over speed
            from ..csum.reference import ceph_crc32c

            def _crc_impl(b):
                return int(ceph_crc32c(0xFFFFFFFF, b))
    b = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    return _crc_impl(b)


# -- transaction (de)serialization ------------------------------------------

def _encode_op(e: Encoder, op: tuple) -> None:
    kind = op[0]
    e.string(kind)
    if kind in ("mkcoll", "rmcoll"):
        e.string(op[1])
    elif kind in ("touch", "remove"):
        e.string(op[1]).string(op[2])
    elif kind == "write":
        e.string(op[1]).string(op[2]).u64(op[3]).blob(op[4].tobytes())
    elif kind == "truncate":
        e.string(op[1]).string(op[2]).u64(op[3])
    elif kind == "setattr":
        e.string(op[1]).string(op[2]).string(op[3]).blob(op[4])
    elif kind == "rmattr":
        e.string(op[1]).string(op[2]).string(op[3])
    elif kind == "omap_set":
        e.string(op[1]).string(op[2])
        e.mapping(op[3], Encoder.blob, Encoder.blob)
    else:
        raise EncodingError(f"unknown op {kind!r}")


def _decode_op(d: Decoder) -> tuple:
    kind = d.string()
    if kind in ("mkcoll", "rmcoll"):
        return (kind, d.string())
    if kind in ("touch", "remove"):
        return (kind, d.string(), d.string())
    if kind == "write":
        cid, oid, off = d.string(), d.string(), d.u64()
        data = np.frombuffer(d.blob(), dtype=np.uint8).copy()
        return (kind, cid, oid, off, data)
    if kind == "truncate":
        return (kind, d.string(), d.string(), d.u64())
    if kind == "setattr":
        return (kind, d.string(), d.string(), d.string(), d.blob())
    if kind == "rmattr":
        return (kind, d.string(), d.string(), d.string())
    if kind == "omap_set":
        return (kind, d.string(), d.string(),
                d.mapping(Decoder.blob, Decoder.blob))
    raise EncodingError(f"unknown op {kind!r}")


def _encode_txn(txn: Transaction) -> bytes:
    e = Encoder()
    e.start(1, 1)
    e.list(txn.ops, _encode_op)
    e.finish()
    return e.bytes()


def _decode_txn(body: bytes) -> Transaction:
    d = Decoder(body)
    d.start(1)
    txn = Transaction()
    txn.ops = d.list(_decode_op)
    d.finish()
    return txn


class TinStore:
    """File-backed ObjectStore: WAL + checkpoint durability, RAM-mirror
    serving, crc32c verify-on-read. Interface == MemStore."""

    def __init__(self, path: str, o_dsync: bool = False,
                 verify_reads: bool = True,
                 wal_max_bytes: int = 64 << 20):
        self.path = path
        self.o_dsync = o_dsync
        self.verify_reads = verify_reads
        self.wal_max_bytes = wal_max_bytes
        self._lock = threading.RLock()
        self._mem: MemStore | None = None
        self._crcs: dict[tuple[str, str], int] = {}
        self._seq = 0              # last committed WAL seq
        self._wal_f = None
        os.makedirs(path, exist_ok=True)
        self.mount()

    # -- paths ---------------------------------------------------------------

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.path, "ckpt")

    # -- lifecycle -----------------------------------------------------------

    def mount(self) -> None:
        """Load checkpoint (verify every object), replay WAL tail."""
        with self._lock:
            self._mem = MemStore()
            self._crcs = {}
            self._seq = 0
            base_seq = self._load_checkpoint()
            self._seq = base_seq
            self._replay_wal(base_seq)
            self._wal_f = open(self._wal_path, "ab")

    @property
    def is_down(self) -> bool:
        """True between crash()/umount() and the next (re)mount()."""
        return self._mem is None

    def crash(self) -> None:
        """SIGKILL semantics: drop RAM state and handles, NO flush, NO
        checkpoint. Only bytes already written to the files survive."""
        with self._lock:
            if self._wal_f is not None:
                try:
                    self._wal_f.close()   # data already flushed per-commit;
                except OSError:           # close() loses nothing extra
                    pass
                self._wal_f = None
            self._mem = None
            self._crcs = {}

    def remount(self) -> None:
        """Restart after crash(): recover purely from disk."""
        self.mount()

    def umount(self) -> None:
        """Clean shutdown: checkpoint then release handles."""
        with self._lock:
            self.checkpoint()
            self._wal_f.close()
            self._wal_f = None
            self._mem = None
            self._crcs = {}

    def _alive(self) -> MemStore:
        if self._mem is None:
            raise RuntimeError(f"TinStore {self.path} is down "
                               f"(crashed/umounted; remount() first)")
        return self._mem

    # -- WAL -----------------------------------------------------------------

    def _append_record(self, body: bytes) -> None:
        self._seq += 1
        hdr = _REC_HDR.pack(_REC_MAGIC, self._seq, len(body))
        rec = hdr + body
        rec += struct.pack("<I", _crc32c(rec))
        self._wal_f.write(rec)
        self._wal_f.flush()                      # survives process kill
        if self.o_dsync:
            os.fsync(self._wal_f.fileno())       # survives machine crash

    def _scan_wal(self):
        """Yield (seq, body) for every valid record; returns via
        StopIteration the (good_bytes, torn_tail, error) triple."""
        try:
            with open(self._wal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return 0, False, None
        off = 0
        n = len(raw)
        while off < n:
            if off + _REC_HDR.size + 4 > n:
                return off, True, None           # torn header
            magic, seq, blen = _REC_HDR.unpack_from(raw, off)
            if magic != _REC_MAGIC:
                return off, False, f"bad magic at {off}"
            end = off + _REC_HDR.size + blen + 4
            if end > n:
                return off, True, None           # torn body
            (crc,) = struct.unpack_from("<I", raw, end - 4)
            if _crc32c(raw[off:end - 4]) != crc:
                # a bad crc at the very tail is a torn append; bad crc
                # FOLLOWED by more bytes is real corruption
                return off, end >= n, (None if end >= n
                                       else f"crc mismatch at {off}")
            yield seq, raw[off + _REC_HDR.size:end - 4]
            off = end
        return off, False, None

    def _replay_wal(self, base_seq: int) -> None:
        gen = self._scan_wal()
        while True:
            try:
                seq, body = next(gen)
            except StopIteration as stop:
                good_bytes, torn, err = stop.value
                if err:
                    raise TinStoreCorruption(
                        f"{self._wal_path}: {err} (mid-log corruption; "
                        f"run fsck)")
                if torn:
                    # crash mid-append: drop the partial record
                    with open(self._wal_path, "ab") as f:
                        f.truncate(good_bytes)
                return
            if seq <= base_seq:
                continue                         # checkpoint covers it
            if seq != self._seq + 1:
                raise TinStoreCorruption(
                    f"{self._wal_path}: seq jump {self._seq} -> {seq}")
            txn = _decode_txn(body)
            for op in txn.ops:
                self._mem._apply(op)
            self._mem.committed_txns += 1
            self._seq = seq
            self._note_crcs(txn)

    # -- checkpoint ----------------------------------------------------------

    def checkpoint(self) -> None:
        """Serialize full state atomically; then reset the WAL. Crash
        windows: before rename -> old ckpt + full WAL; after rename,
        before reset -> new ckpt + stale WAL records whose seqs are
        skipped at replay. Either way state is exact."""
        with self._lock:
            mem = self._alive()
            e = Encoder()
            e.start(_CKPT_VERSION, 1)
            e.u64(self._seq)
            e.u64(mem.committed_txns)
            e.u32(len(mem.collections))
            for cid in sorted(mem.collections):
                e.string(cid)
                coll = mem.collections[cid]
                e.u32(len(coll))
                for oid in sorted(coll):
                    o = coll[oid]
                    e.string(oid)
                    e.blob(o.data.tobytes())
                    e.u32(self._crcs.get((cid, oid), 0))
                    e.mapping(o.xattrs, Encoder.string, Encoder.blob)
                    e.mapping(o.omap, Encoder.blob, Encoder.blob)
            e.finish()
            body = e.bytes()
            body += struct.pack("<I", _crc32c(body))
            tmp = self._ckpt_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path)
            if self._wal_f is not None:
                self._wal_f.close()
            self._wal_f = open(self._wal_path, "wb")  # reset the log

    def _load_checkpoint(self) -> int:
        try:
            with open(self._ckpt_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return 0
        if len(raw) < 4:
            raise TinStoreCorruption(f"{self._ckpt_path}: truncated")
        (crc,) = struct.unpack_from("<I", raw, len(raw) - 4)
        if _crc32c(raw[:-4]) != crc:
            raise TinStoreCorruption(f"{self._ckpt_path}: file seal "
                                     f"crc mismatch")
        d = Decoder(raw[:-4])
        d.start(_CKPT_VERSION)
        seq = d.u64()
        self._mem.committed_txns = d.u64()
        for _ in range(d.u32()):
            cid = d.string()
            coll = self._mem.collections.setdefault(cid, {})
            for _ in range(d.u32()):
                oid = d.string()
                data = np.frombuffer(d.blob(), dtype=np.uint8).copy()
                want = d.u32()
                got = _crc32c(data)
                if got != want:
                    raise TinStoreCorruption(
                        f"{self._ckpt_path}: {cid}/{oid} data crc "
                        f"{got:#x} != stored {want:#x}")
                xattrs = d.mapping(Decoder.string, Decoder.blob)
                omap = d.mapping(Decoder.blob, Decoder.blob)
                coll[oid] = _Object(data=data, xattrs=xattrs, omap=omap)
                self._crcs[(cid, oid)] = want
        d.finish()
        return seq

    # -- transactional write path -------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            mem = self._alive()
            mem._validate(txn)
            self._append_record(_encode_txn(txn))   # WAL first
            for op in txn.ops:
                mem._apply(op)
            mem.committed_txns += 1
            self._note_crcs(txn)
            if self._wal_f.tell() >= self.wal_max_bytes:
                self.checkpoint()

    def _note_crcs(self, txn: Transaction) -> None:
        """Refresh the per-object crc for every object a txn touched."""
        touched: set[tuple[str, str]] = set()
        for op in txn.ops:
            kind = op[0]
            if kind == "rmcoll":
                cid = op[1]
                self._crcs = {k: v for k, v in self._crcs.items()
                              if k[0] != cid}
            elif kind == "remove":
                self._crcs.pop((op[1], op[2]), None)
                touched.discard((op[1], op[2]))
            elif kind in ("write", "truncate", "touch", "setattr",
                          "rmattr", "omap_set"):
                touched.add((op[1], op[2]))
        for cid, oid in touched:
            coll = self._mem.collections.get(cid)
            if coll is not None and oid in coll:
                self._crcs[(cid, oid)] = _crc32c(coll[oid].data)

    # -- reads (verify-on-read) ----------------------------------------------

    def _verify(self, cid: str, oid: str, o: _Object) -> None:
        want = self._crcs.get((cid, oid))
        if want is None:
            return                 # object predates crc tracking: skip
        got = _crc32c(o.data)
        if got != want:
            raise TinStoreCorruption(
                f"{cid}/{oid}: crc {got:#x} != expected {want:#x} "
                f"(verify-on-read)")

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> np.ndarray:
        with self._lock:
            mem = self._alive()
            o = mem._obj(cid, oid)
            if self.verify_reads:
                self._verify(cid, oid, o)
            if length is None:
                return o.data[offset:].copy()
            return o.data[offset:offset + length].copy()

    def stat(self, cid: str, oid: str) -> int:
        return self._alive().stat(cid, oid)

    def getattr(self, cid: str, oid: str, key: str) -> bytes:
        return self._alive().getattr(cid, oid, key)

    def exists(self, cid: str, oid: str) -> bool:
        return self._alive().exists(cid, oid)

    def list_objects(self, cid: str) -> list[str]:
        return self._alive().list_objects(cid)

    def list_collections(self) -> list[str]:
        return self._alive().list_collections()

    @property
    def collections(self):
        """Direct state access, like MemStore.collections — the tests
        and scrub paths poke objects through this; mutations made here
        bypass the WAL on purpose (that's what corruption IS)."""
        return self._alive().collections

    @property
    def committed_txns(self) -> int:
        return self._alive().committed_txns

    @committed_txns.setter
    def committed_txns(self, v: int) -> None:
        self._alive().committed_txns = v

    # -- fsck ----------------------------------------------------------------

    @staticmethod
    def fsck(path: str) -> dict:
        """Offline integrity audit (ref: BlueStore::fsck): re-read the
        checkpoint + WAL into a scratch state, verify every crc, and
        report without mutating anything on disk."""
        report = {"objects": 0, "bad_objects": [], "wal_records": 0,
                  "torn_tail": False, "errors": []}
        scratch = TinStore.__new__(TinStore)
        scratch.path = path
        scratch._lock = threading.RLock()
        scratch._mem = MemStore()
        scratch._crcs = {}
        scratch._seq = 0
        scratch._wal_f = None
        try:
            base = scratch._load_checkpoint()
        except TinStoreCorruption as e:
            report["errors"].append(str(e))
            return report
        gen = scratch._scan_wal()
        seq = base
        while True:
            try:
                rseq, body = next(gen)
            except StopIteration as stop:
                _, torn, err = stop.value
                report["torn_tail"] = torn
                if err:
                    report["errors"].append(err)
                break
            if rseq <= base:
                continue
            if rseq != seq + 1:
                report["errors"].append(f"seq jump {seq} -> {rseq}")
                break
            try:
                txn = _decode_txn(body)
                for op in txn.ops:
                    scratch._mem._apply(op)
                scratch._note_crcs(txn)
            except (EncodingError, KeyError) as e:
                report["errors"].append(f"record {rseq}: {e}")
                break
            seq = rseq
            report["wal_records"] += 1
        for cid, coll in scratch._mem.collections.items():
            for oid, o in coll.items():
                report["objects"] += 1
                want = scratch._crcs.get((cid, oid))
                if want is not None and _crc32c(o.data) != want:
                    report["bad_objects"].append(f"{cid}/{oid}")
        return report
