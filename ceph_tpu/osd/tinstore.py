"""TinStore — the persistent, crash-consistent ObjectStore.

A file-backed store behind the exact ObjectStore interface MemStore
implements, so every backend/cluster path runs unchanged on either
(the reference parameterizes one suite over MemStore and BlueStore the
same way; ref: src/test/objectstore/store_test.cc).

Design (the load-bearing slice of the reference's L4, ref:
src/os/bluestore/BlueStore.cc _do_write/_do_read/_kv_sync_thread,
BitmapAllocator, _verify_csum, BlueStore::fsck; transactional contract
ref: src/os/ObjectStore.h Transaction/queue_transaction):

* BLOCK PLANE. Object bytes live in `block.dev`, a flat data device,
  in extents handed out by an in-RAM extent allocator (4 KiB units,
  first-fit free list with coalescing — the BitmapAllocator role).
  Data writes are COPY-ON-WRITE: a write stages the object's new
  bytes into a FRESH extent (never over live data), so torn data
  writes can't damage committed state. The freelist is not persisted;
  it is derived at mount from the live extent map (and fsck audits
  the same derivation for overlaps/bounds).
* WRITE-AHEAD LOG — metadata only. Every queue_transaction first
  pwrites its staged data extents, then appends ONE length-prefixed,
  crc32c-sealed record of the METADATA mutation (data ops carry
  extent references, not bytes) to `wal.log`, and only then applies
  to the in-RAM metadata. A transaction is wholly in the WAL or
  absent; a crash between data pwrite and WAL append leaves only
  unreferenced extents, which the derived allocator reclaims at
  mount. `flush()` per commit = process-kill consistency;
  `o_dsync=True` adds fsync (machine-crash consistency).
* BOUNDED BUFFER CACHE. Reads are served from an LRU byte cache with
  a hard byte budget (`cache_bytes`); misses pread the device. The
  serving plane is NOT a store-sized RAM mirror: datasets many times
  the cache budget serve correctly with eviction (BlueStore's
  2Q/buffer cache role, simplified to LRU).
* METADATA CHECKPOINTS. When the WAL exceeds `wal_max_bytes`, the
  metadata (extent refs, sizes, crcs, xattrs, omap) is serialized to
  `ckpt.tmp` and atomically renamed over `ckpt`; the WAL resets.
  Checkpoint cost is O(metadata), independent of data volume — the
  r3 whole-store serialize is gone. Replay seq-skips records the
  checkpoint covers, so a crash between rename and reset
  double-applies nothing.
* INLINE COMPRESSION (opt-in). With `compression=` ("zlib"/"lzma"),
  blobs >= compression_min_blob that shrink to at most
  compression_required_ratio of raw are stored COMPRESSED (the
  BlueStore bluestore_compression_* decision, mode=aggressive): the
  device holds the compressed stream in a smaller extent, metadata
  carries (calg, clen, ccrc) alongside the logical crc, reads verify
  the stored bytes, inflate (bounded by the logical size — a bomb
  fails, it doesn't OOM), then verify the logical crc. Blobs that
  don't earn their keep stay raw; reads are transparent either way.
* VERIFY-ON-READ. Each object's crc32c (native C kernel, parity with
  ceph_crc32c) is computed when its bytes are staged and re-checked
  when a read misses the cache (and on every read of cached bytes);
  mismatch raises `TinStoreCorruption` (the _verify_csum -EIO
  analog). `collections[...][...].data` exposes the device bytes as
  a writable memmap view — in-place pokes are REAL on-disk
  corruption (they bypass WAL and crc, and invalidate the cache so
  the next read sees the damage).
* RECOVERY. mount() = load newest valid checkpoint (metadata),
  replay WAL records in seq order (each crc-checked; a torn tail
  record is truncated away), then derive the allocator from the
  surviving extent map.
* FSCK. TinStore.fsck(path) re-reads everything offline: checkpoint
  seal, WAL chain, extent-map audit (overlaps, device bounds), and
  every object's data crc straight from the device.

Process-kill semantics for the chaos tests: crash() drops RAM state
and file handles with NO checkpoint (what SIGKILL leaves behind);
remount() recovers purely from disk. SimCluster(store="tin") routes
kill/revive through these, so thrash survival is a measured property
of the WAL + block plane, not an axiom of the sim.
"""

from __future__ import annotations

import os
import struct
import threading
from collections import OrderedDict
from collections.abc import Mapping

import numpy as np

from ..utils.encoding import Decoder, Encoder, EncodingError
from .memstore import MemStore, Transaction, _Object  # noqa: F401 — _Object
#                      re-exported for store-agnostic test helpers

_REC_MAGIC = 0x544E4952    # "RINT" little-endian: record
_REC_HDR = struct.Struct("<IQI")     # magic, seq, body_len
_CKPT_VERSION = 3   # v3: per-object compression triple (calg, clen, ccrc)
_ALLOC_UNIT = 4096


class TinStoreCorruption(IOError):
    """Checksum/structure mismatch on the read path (-EIO analog)."""


_crc_impl = None


def _crc32c(data) -> int:
    """Whole-buffer crc32c, raw-register convention (seed 0xFFFFFFFF,
    no final inversion) — native C fast path, pure-python fallback."""
    global _crc_impl
    if _crc_impl is None:
        try:
            from ..native import lib
            L = lib()

            def _crc_impl(b, _L=L):
                return int(_L.ec_crc32c(0xFFFFFFFF, b, len(b)))
        except Exception:          # no toolchain: correctness over speed
            from ..csum.reference import ceph_crc32c

            def _crc_impl(b):
                return int(ceph_crc32c(0xFFFFFFFF, b))
    b = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    return _crc_impl(b)


# -- wire transaction (de)serialization --------------------------------------
# Full-data form: MStoreOp frames ship entire Transactions between
# daemons (a peer can't dereference our device offsets). The WAL uses
# the separate metadata-op codec below.

def _encode_op(e: Encoder, op: tuple) -> None:
    kind = op[0]
    e.string(kind)
    if kind in ("mkcoll", "rmcoll"):
        e.string(op[1])
    elif kind in ("touch", "remove", "omap_clear"):
        e.string(op[1]).string(op[2])
    elif kind == "write":
        e.string(op[1]).string(op[2]).u64(op[3]).blob(op[4].tobytes())
    elif kind == "truncate":
        e.string(op[1]).string(op[2]).u64(op[3])
    elif kind == "setattr":
        e.string(op[1]).string(op[2]).string(op[3]).blob(op[4])
    elif kind == "rmattr":
        e.string(op[1]).string(op[2]).string(op[3])
    elif kind == "omap_set":
        e.string(op[1]).string(op[2])
        e.mapping(op[3], Encoder.blob, Encoder.blob)
    elif kind == "omap_rmkeys":
        e.string(op[1]).string(op[2])
        e.list(op[3], Encoder.blob)
    else:
        raise EncodingError(f"unknown op {kind!r}")


def _decode_op(d: Decoder) -> tuple:
    kind = d.string()
    if kind in ("mkcoll", "rmcoll"):
        return (kind, d.string())
    if kind in ("touch", "remove", "omap_clear"):
        return (kind, d.string(), d.string())
    if kind == "write":
        cid, oid, off = d.string(), d.string(), d.u64()
        data = np.frombuffer(d.blob(), dtype=np.uint8).copy()
        return (kind, cid, oid, off, data)
    if kind == "truncate":
        return (kind, d.string(), d.string(), d.u64())
    if kind == "setattr":
        return (kind, d.string(), d.string(), d.string(), d.blob())
    if kind == "rmattr":
        return (kind, d.string(), d.string(), d.string())
    if kind == "omap_set":
        return (kind, d.string(), d.string(),
                d.mapping(Decoder.blob, Decoder.blob))
    if kind == "omap_rmkeys":
        return (kind, d.string(), d.string(), d.list(Decoder.blob))
    raise EncodingError(f"unknown op {kind!r}")


def _encode_txn(txn: Transaction) -> bytes:
    e = Encoder()
    e.start(1, 1)
    e.list(txn.ops, _encode_op)
    e.finish()
    return e.bytes()


def _decode_txn(body: bytes) -> Transaction:
    d = Decoder(body)
    d.start(1)
    txn = Transaction()
    txn.ops = d.list(_decode_op)
    d.finish()
    return txn


# -- WAL metadata-op (de)serialization ---------------------------------------
# Data ops are rewritten to ("setext", cid, oid, doff, dlen, size, crc)
# before logging: the bytes are already on the device, the WAL carries
# only the reference (BlueStore's big-write path: data to fresh blobs,
# metadata through the kv journal).

def _encode_meta_op(e: Encoder, op: tuple) -> None:
    kind = op[0]
    if kind == "setext":
        e.string(kind)
        e.string(op[1]).string(op[2])
        e.u64(op[3]).u64(op[4]).u64(op[5]).u32(op[6])
    elif kind == "setextc":
        # compressed extent: a DISTINCT kind (not extra fields on
        # setext) so stores written before compression existed replay
        # unchanged
        e.string(kind)
        e.string(op[1]).string(op[2])
        e.u64(op[3]).u64(op[4]).u64(op[5]).u32(op[6])
        e.string(op[7]).u64(op[8]).u32(op[9])
    else:
        _encode_op(e, op)


def _decode_meta_op(d: Decoder) -> tuple:
    kind = d.string()
    if kind == "setext":
        return (kind, d.string(), d.string(),
                d.u64(), d.u64(), d.u64(), d.u32())
    if kind == "setextc":
        return (kind, d.string(), d.string(),
                d.u64(), d.u64(), d.u64(), d.u32(),
                d.string(), d.u64(), d.u32())
    if kind in ("mkcoll", "rmcoll"):
        return (kind, d.string())
    if kind in ("touch", "remove", "omap_clear"):
        return (kind, d.string(), d.string())
    if kind == "setattr":
        return (kind, d.string(), d.string(), d.string(), d.blob())
    if kind == "rmattr":
        return (kind, d.string(), d.string(), d.string())
    if kind == "omap_set":
        return (kind, d.string(), d.string(),
                d.mapping(Decoder.blob, Decoder.blob))
    if kind == "omap_rmkeys":
        return (kind, d.string(), d.string(), d.list(Decoder.blob))
    raise EncodingError(f"unknown meta op {kind!r}")


def _encode_meta_txn(ops: list[tuple]) -> bytes:
    e = Encoder()
    e.start(1, 1)
    e.list(ops, _encode_meta_op)
    e.finish()
    return e.bytes()


def _decode_meta_txn(body: bytes) -> list[tuple]:
    d = Decoder(body)
    d.start(1)
    ops = d.list(_decode_meta_op)
    d.finish()
    return ops


# -- block plane --------------------------------------------------------------

class ExtentAllocator:
    """First-fit free-extent list over the flat data device, 4 KiB
    allocation units, coalescing frees (ref: src/os/bluestore/
    AvlAllocator.cc behaviorally; the freelist is derived, not
    persisted — mount/fsck rebuild it from the live extent map)."""

    def __init__(self, device_size: int = 0):
        self.device_size = int(device_size)
        self._free: list[list[int]] = (
            [[0, self.device_size]] if self.device_size else [])

    @staticmethod
    def round_up(n: int) -> int:
        return (int(n) + _ALLOC_UNIT - 1) // _ALLOC_UNIT * _ALLOC_UNIT

    def used_bytes(self) -> int:
        return self.device_size - sum(ln for _, ln in self._free)

    def reserve(self, off: int, length: int) -> None:
        """Mark [off, off+length) used (mount derivation). Raises
        TinStoreCorruption if any part is not free — that's an extent
        overlap or out-of-device reference in the metadata."""
        if length <= 0:
            return
        end = off + length
        if off < 0 or end > self.device_size:
            raise TinStoreCorruption(
                f"extent [{off},{end}) outside device "
                f"(size {self.device_size})")
        for i, (foff, flen) in enumerate(self._free):
            fend = foff + flen
            if foff <= off and end <= fend:
                repl = []
                if foff < off:
                    repl.append([foff, off - foff])
                if end < fend:
                    repl.append([end, fend - end])
                self._free[i:i + 1] = repl
                return
        raise TinStoreCorruption(
            f"extent [{off},{end}) overlaps another allocation")

    def alloc(self, nbytes: int) -> tuple[int, int]:
        """Return (doff, dlen) with dlen = round_up(nbytes). Grows the
        device (caller must ftruncate to self.device_size after).
        Zero bytes need no extent: empty objects must not pin units."""
        if nbytes <= 0:
            return 0, 0
        need = self.round_up(nbytes)
        for i, (foff, flen) in enumerate(self._free):
            if flen >= need:
                if flen == need:
                    del self._free[i]
                else:
                    self._free[i] = [foff + need, flen - need]
                return foff, need
        doff = self.device_size
        self.device_size += need
        return doff, need

    def free(self, off: int, length: int) -> None:
        if length <= 0:
            return
        end = off + length
        # insert sorted, coalesce neighbors
        import bisect
        idx = bisect.bisect_left(self._free, [off, length])
        self._free.insert(idx, [off, length])
        merged = []
        for seg in self._free:
            if merged and merged[-1][0] + merged[-1][1] >= seg[0]:
                merged[-1][1] = max(merged[-1][1],
                                    seg[0] + seg[1] - merged[-1][0])
            else:
                merged.append(seg)
        self._free = merged
        del end


class _BufferCache:
    """LRU byte cache with a hard budget — the bounded serving plane.
    Objects larger than the whole budget bypass the cache."""

    def __init__(self, budget: int):
        self.budget = int(budget)
        self.total = 0
        self.hits = 0
        self.misses = 0
        self._lru: OrderedDict[tuple, np.ndarray] = OrderedDict()

    def get(self, key) -> np.ndarray | None:
        arr = self._lru.get(key)
        if arr is None:
            self.misses += 1
            return None
        self._lru.move_to_end(key)
        self.hits += 1
        return arr

    def put(self, key, arr: np.ndarray) -> None:
        self.drop(key)
        if arr.nbytes > self.budget:
            return
        self._lru[key] = arr
        self.total += arr.nbytes
        while self.total > self.budget and self._lru:
            _, old = self._lru.popitem(last=False)
            self.total -= old.nbytes

    def drop(self, key) -> None:
        old = self._lru.pop(key, None)
        if old is not None:
            self.total -= old.nbytes

    def drop_coll(self, cid: str) -> None:
        for key in [k for k in self._lru if k[0] == cid]:
            self.drop(key)

    def clear(self) -> None:
        self._lru.clear()
        self.total = 0


class _TinObject:
    """Metadata record: where the bytes live, how big, their crc.
    Compressed blobs (calg != "") additionally carry the STORED
    length (clen) and a crc over the stored bytes (ccrc) — the
    BlueStore per-blob compressed_length + csum-on-stored-data pair;
    `crc` is always over the LOGICAL bytes."""

    __slots__ = ("size", "doff", "dlen", "crc", "xattrs", "omap",
                 "calg", "clen", "ccrc")

    def __init__(self, size=0, doff=0, dlen=0, crc=0,
                 xattrs=None, omap=None, calg="", clen=0, ccrc=0):
        self.size, self.doff, self.dlen, self.crc = size, doff, dlen, crc
        self.xattrs: dict[str, bytes] = xattrs if xattrs is not None else {}
        self.omap: dict[bytes, bytes] = omap if omap is not None else {}
        self.calg, self.clen, self.ccrc = calg, clen, ccrc

    @property
    def stored_len(self) -> int:
        return self.clen if self.calg else self.size


# -- collections view (test/scrub poke surface) -------------------------------

class _ObjProxy:
    """MemStore-_Object-shaped view of one object. `.data` is a
    writable memmap straight onto the device extent: in-place pokes
    are genuine on-disk corruption (no WAL, no crc update); the cache
    entry is invalidated so the next read sees the damage."""

    __slots__ = ("_st", "_cid", "_oid")

    def __init__(self, st: "TinStore", cid: str, oid: str):
        self._st, self._cid, self._oid = st, cid, oid

    def _meta(self) -> _TinObject:
        return self._st._alive()[self._cid][self._oid]

    @property
    def data(self) -> np.ndarray:
        o = self._meta()
        self._st._cache.drop((self._cid, self._oid))
        if o.size == 0:
            return np.zeros(0, dtype=np.uint8)
        # the STORED bytes (compressed blobs expose the compressed
        # stream): pokes are device-plane damage either way, caught
        # by ccrc (compressed) or crc (raw) on the next read
        return np.memmap(self._st._dev_path, dtype=np.uint8, mode="r+",
                         offset=o.doff, shape=(o.stored_len,))

    @property
    def xattrs(self) -> dict[str, bytes]:
        return self._meta().xattrs

    @property
    def omap(self) -> dict[bytes, bytes]:
        return self._meta().omap


class _CollView(Mapping):
    def __init__(self, st: "TinStore", cid: str):
        self._st, self._cid = st, cid

    def _coll(self):
        return self._st._alive()[self._cid]

    def __getitem__(self, oid: str) -> _ObjProxy:
        self._coll()[oid]            # KeyError propagates
        return _ObjProxy(self._st, self._cid, oid)

    def __iter__(self):
        return iter(self._coll())

    def __len__(self):
        return len(self._coll())


class _CollectionsView(Mapping):
    def __init__(self, st: "TinStore"):
        self._st = st

    def __getitem__(self, cid: str) -> _CollView:
        self._st._alive()[cid]       # KeyError propagates
        return _CollView(self._st, cid)

    def __iter__(self):
        return iter(self._st._alive())

    def __len__(self):
        return len(self._st._alive())


# -- the store ----------------------------------------------------------------

class TinStore:
    """File-backed ObjectStore: block-plane data device + extent
    allocator, metadata WAL + checkpoints, bounded LRU buffer cache,
    crc32c verify-on-read. Interface == MemStore."""

    COMPRESSION_ALGS = ("zlib", "lzma")

    def __init__(self, path: str, o_dsync: bool = False,
                 verify_reads: bool = True,
                 wal_max_bytes: int = 64 << 20,
                 cache_bytes: int = 64 << 20,
                 compression: str | None = None,
                 compression_min_blob: int = 4096,
                 compression_required_ratio: float = 0.875):
        if compression is not None \
                and compression not in self.COMPRESSION_ALGS:
            raise ValueError(f"unknown compression {compression!r}; "
                             f"use one of {self.COMPRESSION_ALGS}")
        self.path = path
        self.o_dsync = o_dsync
        self.verify_reads = verify_reads
        self.wal_max_bytes = wal_max_bytes
        self.cache_bytes = cache_bytes
        # inline compression (ref: BlueStore _do_write compression
        # decision: bluestore_compression_{algorithm,min_blob_size,
        # required_ratio}): blobs >= min_blob that shrink to at most
        # required_ratio of raw are stored compressed; everything
        # else stays raw. Reads are transparent either way.
        self.compression = compression
        self.compression_min_blob = compression_min_blob
        self.compression_required_ratio = compression_required_ratio
        self.compress_stats = {"compressed_blobs": 0, "raw_blobs": 0,
                               "logical_bytes": 0, "stored_bytes": 0}
        self._lock = threading.RLock()
        self._meta: dict[str, dict[str, _TinObject]] | None = None
        self._alloc = ExtentAllocator()
        self._cache = _BufferCache(cache_bytes)
        self._seq = 0              # last committed WAL seq
        self._wal_f = None
        self._dev_fd: int | None = None
        self.committed_txns = 0
        os.makedirs(path, exist_ok=True)
        self.mount()

    # -- paths ---------------------------------------------------------------

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.path, "ckpt")

    @property
    def _dev_path(self) -> str:
        return os.path.join(self.path, "block.dev")

    # -- lifecycle -----------------------------------------------------------

    def mount(self) -> None:
        """Load checkpoint metadata, replay WAL tail, derive the
        allocator from the surviving extent map, open the device."""
        with self._lock:
            self._meta = {}
            self._cache = _BufferCache(self.cache_bytes)
            self._seq = 0
            self.committed_txns = 0
            self._dev_fd = os.open(self._dev_path,
                                   os.O_RDWR | os.O_CREAT, 0o644)
            base_seq = self._load_checkpoint()
            self._seq = base_seq
            self._replay_wal(base_seq)
            self._derive_allocator()
            self._wal_f = open(self._wal_path, "ab")

    def _derive_allocator(self) -> None:
        dev_size = os.fstat(self._dev_fd).st_size
        # metadata may reference past a file whose tail grow raced a
        # crash — impossible forward (grow precedes WAL append), so a
        # larger-than-file reference is corruption; reserve() raises.
        span = ExtentAllocator.round_up(dev_size)
        alloc = ExtentAllocator(span)
        for coll in self._meta.values():
            for o in coll.values():
                if o.dlen:
                    alloc.reserve(o.doff, o.dlen)
        if span > dev_size:
            os.ftruncate(self._dev_fd, span)
        self._alloc = alloc

    @property
    def is_down(self) -> bool:
        """True between crash()/umount() and the next (re)mount()."""
        return self._meta is None

    def crash(self) -> None:
        """SIGKILL semantics: drop RAM state and handles, NO flush, NO
        checkpoint. Only bytes already written to the files survive."""
        with self._lock:
            if self._wal_f is not None:
                try:
                    self._wal_f.close()   # data already flushed per-commit;
                except OSError:           # close() loses nothing extra
                    pass
                self._wal_f = None
            if self._dev_fd is not None:
                try:
                    os.close(self._dev_fd)
                except OSError:
                    pass
                self._dev_fd = None
            self._meta = None
            self._cache.clear()

    def remount(self) -> None:
        """Restart after crash(): recover purely from disk."""
        self.mount()

    def umount(self) -> None:
        """Clean shutdown: checkpoint then release handles."""
        with self._lock:
            self.checkpoint()
            self._wal_f.close()
            self._wal_f = None
            os.close(self._dev_fd)
            self._dev_fd = None
            self._meta = None
            self._cache.clear()

    def _alive(self) -> dict[str, dict[str, _TinObject]]:
        if self._meta is None:
            raise RuntimeError(f"TinStore {self.path} is down "
                               f"(crashed/umounted; remount() first)")
        return self._meta

    # -- WAL -----------------------------------------------------------------

    def _append_record(self, body: bytes) -> None:
        self._seq += 1
        hdr = _REC_HDR.pack(_REC_MAGIC, self._seq, len(body))
        rec = hdr + body
        rec += struct.pack("<I", _crc32c(rec))
        self._wal_f.write(rec)
        self._wal_f.flush()                      # survives process kill
        if self.o_dsync:
            os.fsync(self._wal_f.fileno())       # survives machine crash

    def _scan_wal(self):
        """Yield (seq, body) for every valid record; returns via
        StopIteration the (good_bytes, torn_tail, error) triple."""
        try:
            with open(self._wal_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return 0, False, None
        off = 0
        n = len(raw)
        while off < n:
            if off + _REC_HDR.size + 4 > n:
                return off, True, None           # torn header
            magic, seq, blen = _REC_HDR.unpack_from(raw, off)
            if magic != _REC_MAGIC:
                return off, False, f"bad magic at {off}"
            end = off + _REC_HDR.size + blen + 4
            if end > n:
                return off, True, None           # torn body
            (crc,) = struct.unpack_from("<I", raw, end - 4)
            if _crc32c(raw[off:end - 4]) != crc:
                # a bad crc at the very tail is a torn append; bad crc
                # FOLLOWED by more bytes is real corruption
                return off, end >= n, (None if end >= n
                                       else f"crc mismatch at {off}")
            yield seq, raw[off + _REC_HDR.size:end - 4]
            off = end
        return off, False, None

    def _replay_wal(self, base_seq: int) -> None:
        gen = self._scan_wal()
        while True:
            try:
                seq, body = next(gen)
            except StopIteration as stop:
                good_bytes, torn, err = stop.value
                if err:
                    raise TinStoreCorruption(
                        f"{self._wal_path}: {err} (mid-log corruption; "
                        f"run fsck)")
                if torn:
                    # crash mid-append: drop the partial record
                    with open(self._wal_path, "ab") as f:
                        f.truncate(good_bytes)
                return
            if seq <= base_seq:
                continue                         # checkpoint covers it
            if seq != self._seq + 1:
                raise TinStoreCorruption(
                    f"{self._wal_path}: seq jump {self._seq} -> {seq}")
            for op in _decode_meta_txn(body):
                self._apply_meta(op, live=False)
            self.committed_txns += 1
            self._seq = seq

    # -- checkpoint ----------------------------------------------------------

    def checkpoint(self) -> None:
        """Serialize METADATA atomically (extent refs, not data — cost
        is independent of store size); then reset the WAL. Crash
        windows: before rename -> old ckpt + full WAL; after rename,
        before reset -> new ckpt + stale WAL records whose seqs are
        skipped at replay. Either way state is exact."""
        with self._lock:
            meta = self._alive()
            e = Encoder()
            e.start(_CKPT_VERSION, _CKPT_VERSION)
            e.u64(self._seq)
            e.u64(self.committed_txns)
            e.u32(len(meta))
            for cid in sorted(meta):
                e.string(cid)
                coll = meta[cid]
                e.u32(len(coll))
                for oid in sorted(coll):
                    o = coll[oid]
                    e.string(oid)
                    e.u64(o.size).u64(o.doff).u64(o.dlen).u32(o.crc)
                    e.mapping(o.xattrs, Encoder.string, Encoder.blob)
                    e.mapping(o.omap, Encoder.blob, Encoder.blob)
                    # v3: compression triple
                    e.string(o.calg).u64(o.clen).u32(o.ccrc)
            e.finish()
            body = e.bytes()
            body += struct.pack("<I", _crc32c(body))
            tmp = self._ckpt_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path)
            if self._wal_f is not None:
                self._wal_f.close()
            self._wal_f = open(self._wal_path, "wb")  # reset the log

    def _load_checkpoint(self) -> int:
        try:
            with open(self._ckpt_path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return 0
        if len(raw) < 4:
            raise TinStoreCorruption(f"{self._ckpt_path}: truncated")
        (crc,) = struct.unpack_from("<I", raw, len(raw) - 4)
        if _crc32c(raw[:-4]) != crc:
            raise TinStoreCorruption(f"{self._ckpt_path}: file seal "
                                     f"crc mismatch")
        d = Decoder(raw[:-4])
        v = d.start(_CKPT_VERSION)
        seq = d.u64()
        self.committed_txns = d.u64()
        for _ in range(d.u32()):
            cid = d.string()
            coll = self._meta.setdefault(cid, {})
            for _ in range(d.u32()):
                oid = d.string()
                size, doff, dlen, ocrc = d.u64(), d.u64(), d.u64(), d.u32()
                xattrs = d.mapping(Decoder.string, Decoder.blob)
                omap = d.mapping(Decoder.blob, Decoder.blob)
                if v >= 3:
                    calg, clen, ccrc = d.string(), d.u64(), d.u32()
                else:
                    calg, clen, ccrc = "", 0, 0
                coll[oid] = _TinObject(size, doff, dlen, ocrc,
                                       xattrs, omap, calg, clen, ccrc)
        d.finish()
        return seq

    # -- transactional write path -------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            self._alive()
            self._validate(txn)
            staged: dict[tuple[str, str], np.ndarray] = {}
            # objects removed EARLIER IN THIS TXN: a later write must
            # start from empty, not resurrect the pre-txn bytes
            # (MemStore applies ops in order; staging must match)
            gone: set[tuple[str, str]] = set()
            gone_colls: set[str] = set()
            new_extents: list[tuple[int, int]] = []
            meta_ops: list[tuple] = []
            try:
                for op in txn.ops:
                    kind = op[0]
                    if kind == "remove":
                        gone.add((op[1], op[2]))
                        staged.pop((op[1], op[2]), None)
                    elif kind == "rmcoll":
                        # stays in gone_colls even if re-created later
                        # in the txn: the fresh collection is EMPTY,
                        # pre-txn objects must not show through it
                        gone_colls.add(op[1])
                        for key in [k for k in staged if k[0] == op[1]]:
                            del staged[key]
                    if kind == "write":
                        _, cid, oid, woff, data = op
                        cur = self._staged_bytes(staged, gone,
                                                 gone_colls, cid, oid)
                        end = woff + len(data)
                        if end > len(cur):
                            grown = np.zeros(end, dtype=np.uint8)
                            grown[:len(cur)] = cur
                            cur = grown
                        else:
                            cur = cur.copy()
                        cur[woff:end] = data
                        meta_ops.append(self._stage(
                            staged, new_extents, cid, oid, cur))
                    elif kind == "truncate":
                        _, cid, oid, size = op
                        cur = self._staged_bytes(staged, gone,
                                                 gone_colls, cid, oid)
                        if size <= len(cur):
                            cur = cur[:size].copy()
                        else:
                            grown = np.zeros(size, dtype=np.uint8)
                            grown[:len(cur)] = cur
                            cur = grown
                        meta_ops.append(self._stage(
                            staged, new_extents, cid, oid, cur))
                    else:
                        meta_ops.append(op)
            except Exception:
                for doff, dlen in new_extents:
                    self._alloc.free(doff, dlen)
                raise
            if self.o_dsync and new_extents:
                os.fsync(self._dev_fd)     # data durable BEFORE the WAL
            self._append_record(_encode_meta_txn(meta_ops))
            for op in meta_ops:
                self._apply_meta(op, live=True)
            for key, arr in staged.items():
                cid, oid = key
                if cid in self._meta and oid in self._meta[cid]:
                    self._cache.put(key, arr)
            self.committed_txns += 1
            if self._wal_f.tell() >= self.wal_max_bytes:
                self.checkpoint()

    def _staged_bytes(self, staged, gone, gone_colls,
                      cid, oid) -> np.ndarray:
        key = (cid, oid)
        if key in staged:
            return staged[key]
        if key in gone or cid in gone_colls:
            return np.zeros(0, dtype=np.uint8)
        coll = self._meta.get(cid, {})
        if oid in coll:
            return self._object_bytes(cid, oid)
        return np.zeros(0, dtype=np.uint8)

    @staticmethod
    def _compress(alg: str, raw: bytes) -> bytes:
        if alg == "zlib":
            import zlib
            return zlib.compress(raw, 3)
        import lzma
        return lzma.compress(raw, preset=0)

    @staticmethod
    def _decompress(alg: str, stored: bytes, logical_size: int) -> bytes:
        """Bounded decompress: never inflate past the metadata's
        logical size (a corrupt/bombed blob fails, it doesn't OOM)."""
        if alg == "zlib":
            import zlib
            dec = zlib.decompressobj()
        else:
            import lzma
            dec = lzma.LZMADecompressor()
        out = dec.decompress(stored, logical_size + 1)
        return out

    def _stage(self, staged, new_extents, cid, oid,
               arr: np.ndarray) -> tuple:
        """COW the object's new bytes into a fresh extent; return the
        setext/setextc metadata op. Nothing commits until the WAL
        record. Compression happens HERE (the _do_write decision):
        the device and the crc-on-stored-bytes see compressed data,
        the cache and the logical crc see raw data."""
        stored = arr.tobytes()
        calg = ""
        if self.compression is not None \
                and len(arr) >= self.compression_min_blob:
            comp = self._compress(self.compression, stored)
            if len(comp) <= self.compression_required_ratio * len(arr):
                stored, calg = comp, self.compression
        doff, dlen = self._alloc.alloc(len(stored))
        if self._alloc.device_size > os.fstat(self._dev_fd).st_size:
            os.ftruncate(self._dev_fd, self._alloc.device_size)
        if stored:
            os.pwrite(self._dev_fd, stored, doff)
        new_extents.append((doff, dlen))
        staged[(cid, oid)] = arr
        st = self.compress_stats
        st["logical_bytes"] += len(arr)
        st["stored_bytes"] += len(stored)
        if calg:
            st["compressed_blobs"] += 1
            return ("setextc", cid, oid, doff, dlen, len(arr),
                    _crc32c(arr), calg, len(stored),
                    _crc32c(np.frombuffer(stored, np.uint8)))
        st["raw_blobs"] += 1
        return ("setext", cid, oid, doff, dlen, len(arr), _crc32c(arr))

    def _validate(self, txn: Transaction) -> None:
        # the ObjectStore contract: ops referencing missing
        # collections are caller bugs -> abort before mutating anything
        cols = set(self._meta)
        for op in txn.ops:
            kind = op[0]
            if kind == "mkcoll":
                cols.add(op[1])
            elif kind == "rmcoll":
                if op[1] not in cols:
                    raise KeyError(f"rmcoll: no collection {op[1]!r}")
                cols.discard(op[1])
            else:
                if op[1] not in cols:
                    raise KeyError(f"{kind}: no collection {op[1]!r}")

    def _apply_meta(self, op: tuple, live: bool) -> None:
        """Apply one metadata op. `live` frees replaced extents back
        to the allocator and maintains the cache; replay skips both
        (the allocator is derived after replay, the cache is cold)."""
        meta = self._meta
        kind = op[0]
        if kind == "mkcoll":
            meta.setdefault(op[1], {})
        elif kind == "rmcoll":
            coll = meta.pop(op[1])
            if live:
                for o in coll.values():
                    if o.dlen:
                        self._alloc.free(o.doff, o.dlen)
                self._cache.drop_coll(op[1])
        elif kind == "touch":
            meta[op[1]].setdefault(op[2], _TinObject())
        elif kind in ("setext", "setextc"):
            _, cid, oid, doff, dlen, size, crc = op[:7]
            o = meta[cid].setdefault(oid, _TinObject())
            if live and o.dlen and (o.doff, o.dlen) != (doff, dlen):
                self._alloc.free(o.doff, o.dlen)
            o.doff, o.dlen, o.size, o.crc = doff, dlen, size, crc
            if kind == "setextc":
                o.calg, o.clen, o.ccrc = op[7], op[8], op[9]
            else:
                o.calg, o.clen, o.ccrc = "", 0, 0
        elif kind == "remove":
            o = meta[op[1]].pop(op[2], None)
            if live:
                if o is not None and o.dlen:
                    self._alloc.free(o.doff, o.dlen)
                self._cache.drop((op[1], op[2]))
        elif kind == "setattr":
            meta[op[1]].setdefault(op[2], _TinObject()) \
                .xattrs[op[3]] = op[4]
        elif kind == "rmattr":
            o = meta[op[1]].get(op[2])
            if o is not None:
                o.xattrs.pop(op[3], None)
        elif kind == "omap_set":
            meta[op[1]].setdefault(op[2], _TinObject()) \
                .omap.update(op[3])
        elif kind == "omap_rmkeys":
            o = meta[op[1]].get(op[2])
            if o is not None:
                for k in op[3]:
                    o.omap.pop(k, None)
        elif kind == "omap_clear":
            o = meta[op[1]].get(op[2])
            if o is not None:
                o.omap.clear()
        else:
            raise ValueError(f"unknown meta op {kind!r}")

    # -- reads (bounded cache + verify-on-read) ------------------------------

    def _object_bytes(self, cid: str, oid: str) -> np.ndarray:
        """Full object bytes via the cache; miss = device pread +
        crc verify + insert (LRU eviction keeps the budget)."""
        key = (cid, oid)
        arr = self._cache.get(key)
        o = self._meta[cid][oid]
        if arr is not None and len(arr) == o.size:
            if self.verify_reads:
                self._verify(cid, oid, arr, o.crc)
            return arr
        if o.size == 0:
            return np.zeros(0, dtype=np.uint8)
        raw = os.pread(self._dev_fd, o.stored_len, o.doff)
        if o.calg:
            # verify the STORED bytes first (device-plane damage is
            # caught before the decompressor sees it), then inflate
            # and verify the logical crc
            if self.verify_reads \
                    and _crc32c(np.frombuffer(raw, np.uint8)) != o.ccrc:
                raise TinStoreCorruption(
                    f"{cid}/{oid}: stored-bytes crc mismatch "
                    f"(compressed blob, verify-on-read)")
            try:
                raw = self._decompress(o.calg, raw, o.size)
            except Exception as e:   # noqa: BLE001 — corrupt stream
                raise TinStoreCorruption(
                    f"{cid}/{oid}: decompress failed: {e}") from None
            if len(raw) != o.size:
                raise TinStoreCorruption(
                    f"{cid}/{oid}: decompressed {len(raw)} bytes, "
                    f"expected {o.size}")
        arr = np.frombuffer(raw, dtype=np.uint8)
        if self.verify_reads:
            self._verify(cid, oid, arr, o.crc)
        self._cache.put(key, arr)
        return arr

    def _verify(self, cid: str, oid: str, arr: np.ndarray,
                want: int) -> None:
        got = _crc32c(arr)
        if got != want:
            raise TinStoreCorruption(
                f"{cid}/{oid}: crc {got:#x} != expected {want:#x} "
                f"(verify-on-read)")

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> np.ndarray:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            data = self._object_bytes(cid, oid)
            if length is None:
                return data[offset:].copy()
            return data[offset:offset + length].copy()

    def stat(self, cid: str, oid: str) -> int:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            return coll[oid].size

    def getattr(self, cid: str, oid: str, key: str) -> bytes:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            return coll[oid].xattrs[key]

    def exists(self, cid: str, oid: str) -> bool:
        with self._lock:
            meta = self._alive()
            return cid in meta and oid in meta[cid]

    def list_objects(self, cid: str) -> list[str]:
        with self._lock:
            return sorted(self._alive().get(cid, {}))

    def list_collections(self) -> list[str]:
        with self._lock:
            return sorted(self._alive())

    @property
    def collections(self) -> _CollectionsView:
        """MemStore-shaped state access — the tests and scrub paths
        poke objects through this; `.data` mutations write the device
        in place, bypassing the WAL and crc on purpose (that's what
        corruption IS)."""
        self._alive()
        return _CollectionsView(self)

    def cache_stats(self) -> dict:
        return {"budget": self._cache.budget, "bytes": self._cache.total,
                "hits": self._cache.hits, "misses": self._cache.misses}

    # -- fsck ----------------------------------------------------------------

    @staticmethod
    def fsck(path: str) -> dict:
        """Offline integrity audit (ref: BlueStore::fsck): checkpoint
        seal, WAL chain, extent-map audit (overlaps / device bounds),
        and every object's data crc read straight from the device —
        without mutating anything."""
        report = {"objects": 0, "bad_objects": [], "wal_records": 0,
                  "torn_tail": False, "errors": [], "extent_errors": [],
                  "device_bytes": 0, "used_bytes": 0}
        scratch = TinStore.__new__(TinStore)
        scratch.path = path
        scratch._lock = threading.RLock()
        scratch._meta = {}
        scratch._cache = _BufferCache(0)
        scratch._alloc = ExtentAllocator()
        scratch._seq = 0
        scratch._wal_f = None
        scratch._dev_fd = None
        scratch.committed_txns = 0
        try:
            base = scratch._load_checkpoint()
        except TinStoreCorruption as e:
            report["errors"].append(str(e))
            return report
        gen = scratch._scan_wal()
        seq = base
        while True:
            try:
                rseq, body = next(gen)
            except StopIteration as stop:
                _, torn, err = stop.value
                report["torn_tail"] = torn
                if err:
                    report["errors"].append(err)
                break
            if rseq <= base:
                continue
            if rseq != seq + 1:
                report["errors"].append(f"seq jump {seq} -> {rseq}")
                break
            try:
                for op in _decode_meta_txn(body):
                    scratch._apply_meta(op, live=False)
            except (EncodingError, KeyError, ValueError) as e:
                report["errors"].append(f"record {rseq}: {e}")
                break
            seq = rseq
            report["wal_records"] += 1
        # extent audit: every referenced extent must be in-bounds and
        # disjoint (reserve() raises on violation)
        try:
            dev_size = os.path.getsize(os.path.join(path, "block.dev"))
        except OSError:
            dev_size = 0
        audit = ExtentAllocator(ExtentAllocator.round_up(dev_size))
        report["device_bytes"] = dev_size
        try:
            dev_fd = os.open(os.path.join(path, "block.dev"),
                             os.O_RDONLY)
        except OSError:
            dev_fd = None
        try:
            for cid, coll in scratch._meta.items():
                for oid, o in coll.items():
                    report["objects"] += 1
                    if o.dlen:
                        try:
                            audit.reserve(o.doff, o.dlen)
                        except TinStoreCorruption as e:
                            report["extent_errors"].append(
                                f"{cid}/{oid}: {e}")
                            continue
                    if o.size and dev_fd is not None:
                        raw = os.pread(dev_fd, o.stored_len, o.doff)
                        sarr = np.frombuffer(raw, np.uint8)
                        if o.calg:
                            # stored-bytes seal first, then inflate
                            # and audit the logical crc too
                            if _crc32c(sarr) != o.ccrc:
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            try:
                                raw = TinStore._decompress(
                                    o.calg, raw, o.size)
                            except Exception:  # noqa: BLE001
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            if len(raw) != o.size:
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            sarr = np.frombuffer(raw, np.uint8)
                        if _crc32c(sarr) != o.crc:
                            report["bad_objects"].append(f"{cid}/{oid}")
        finally:
            if dev_fd is not None:
                os.close(dev_fd)
        report["used_bytes"] = audit.used_bytes()
        return report
