"""TinStore — the persistent, crash-consistent ObjectStore.

A file-backed store behind the exact ObjectStore interface MemStore
implements, so every backend/cluster path runs unchanged on either
(the reference parameterizes one suite over MemStore and BlueStore the
same way; ref: src/test/objectstore/store_test.cc).

Design (the load-bearing slice of the reference's L4, ref:
src/os/bluestore/BlueStore.cc _do_write/_do_read/_kv_sync_thread,
BitmapAllocator, _verify_csum, BlueStore::fsck; transactional contract
ref: src/os/ObjectStore.h Transaction/queue_transaction):

* BLOCK PLANE. Object bytes live in `block.dev`, a flat data device,
  in extents handed out by an in-RAM extent allocator (4 KiB units,
  first-fit free list with coalescing — the BitmapAllocator role).
  Data writes are COPY-ON-WRITE: a write stages the object's new
  bytes into a FRESH extent (never over live data), so torn data
  writes can't damage committed state. The freelist is not persisted;
  it is derived at mount from the live extent map (and fsck audits
  the same derivation for overlaps/bounds).
* KV METADATA PLANE. All metadata — collections, object records
  (extent refs, sizes, crcs, xattrs), and omap — lives in TinDB
  (`ceph_tpu/kv`), the ordered-KV store playing RocksDB's role under
  BlueStore. Three prefixes:
      "C" / cid                 -> b""            (collection exists)
      "O" / cid NUL oid         -> object record  (versioned encode)
      "M" / cid NUL oid NUL key -> omap value     (one entry per key)
  Because the KV space is ORDERED, object listing and omap iteration
  are prefix-bounded iterator walks — paginated listings cost
  O(page), not O(collection) (the flat-dict linear scan this plane
  replaces). Every queue_transaction first pwrites its staged data
  extents, then submits ONE atomic TinDB batch (= one crc32c-sealed
  WAL record in `wal.log`) carrying the metadata mutation, and only
  then applies to the in-RAM mirror. A transaction is wholly in the
  KV WAL or absent; a crash between data pwrite and KV submit leaves
  only unreferenced extents, which the derived allocator reclaims at
  mount. `flush()` per commit = process-kill consistency;
  `o_dsync=True` adds fsync (machine-crash consistency).
* RAM MIRROR. Object records (NOT omap) are mirrored in a dict for
  O(1) hot-path reads (the BlueStore onode cache role); the mirror is
  rebuilt from the KV plane at mount and is never the durability
  story. Omap lives only in TinDB and is read through ordered
  iterators.
* BOUNDED BUFFER CACHE. Reads are served from an LRU byte cache with
  a hard byte budget (`cache_bytes`); misses pread the device. The
  serving plane is NOT a store-sized RAM mirror: datasets many times
  the cache budget serve correctly with eviction (BlueStore's
  2Q/buffer cache role, simplified to LRU).
* SEGMENT FLUSH (the checkpoint role). When the KV WAL exceeds
  `wal_max_bytes` (or TinDB's memtable budget fills), the memtable is
  flushed to a sorted immutable segment, the MANIFEST swaps
  atomically, and the WAL resets. Flush cost is O(memtable) —
  independent of both data volume and total metadata volume; leveled
  compaction folds segments down in the background of the write path.
* INLINE COMPRESSION (opt-in). With `compression=` ("zlib"/"lzma"),
  blobs >= compression_min_blob that shrink to at most
  compression_required_ratio of raw are stored COMPRESSED (the
  BlueStore bluestore_compression_* decision, mode=aggressive): the
  device holds the compressed stream in a smaller extent, metadata
  carries (calg, clen, ccrc) alongside the logical crc, reads verify
  the stored bytes, inflate (bounded by the logical size — a bomb
  fails, it doesn't OOM), then verify the logical crc. Blobs that
  don't earn their keep stay raw; reads are transparent either way.
* VERIFY-ON-READ. Each object's crc32c (native C kernel, parity with
  ceph_crc32c) is computed when its bytes are staged and re-checked
  when a read misses the cache (and on every read of cached bytes);
  mismatch raises `TinStoreCorruption` (the _verify_csum -EIO
  analog). `collections[...][...].data` exposes the device bytes as
  a writable memmap view — in-place pokes are REAL on-disk
  corruption (they bypass WAL and crc, and invalidate the cache so
  the next read sees the damage).
* RECOVERY. mount() = TinDB mount (manifest -> segments -> WAL
  replay, torn tail truncated, mid-log damage fatal), then rebuild
  the RAM mirror from the "C"/"O" prefixes and derive the allocator
  from the surviving extent map.
* LEGACY FORWARD REPLAY. Stores written by the pre-KV TinStore
  (`ckpt` checkpoint + metadata-op WAL) are detected at mount (no
  MANIFEST) and migrated forward: legacy checkpoint + WAL are
  replayed in memory, the resulting state is written as TinDB's
  first segment, and the MANIFEST lands with covered_seq set past
  every legacy record — so the legacy WAL (same record framing) is
  seq-skipped, never misparsed. Crash before the MANIFEST: the
  legacy store is intact and migration re-runs. Crash after: the KV
  store is live. Either way nothing is lost.
* FSCK. TinStore.fsck(path) re-reads everything offline: the KV
  plane (manifest seal, segment seals + ordering, WAL chain) via
  TinDB.fsck, a cross-check of KV against the block plane (omap
  entries must have an object record, object records a collection,
  extents in-bounds and disjoint), and every object's data crc
  straight from the device. Legacy stores get the legacy audit.

Process-kill semantics for the chaos tests: crash() drops RAM state
and file handles with NO flush (what SIGKILL leaves behind);
remount() recovers purely from disk. SimCluster(store="tin") routes
kill/revive through these, so thrash survival is a measured property
of the WAL + block plane, not an axiom of the sim.
"""

from __future__ import annotations

import os
import struct
import threading
from collections import OrderedDict
from collections.abc import Mapping

import numpy as np

from ..kv import TinDB, TinDBCorruption, host_crc32c
from ..kv.tindb import Segment, scan_wal, write_segment
from ..utils.encoding import Decoder, Encoder, EncodingError
from .memstore import MemStore, Transaction, _Object  # noqa: F401 — _Object
#                      re-exported for store-agnostic test helpers

_CKPT_VERSION = 3   # final LEGACY checkpoint version (pre-KV stores)
_OBJ_VERSION = 1    # "O"-record encode version
_ALLOC_UNIT = 4096


class TinStoreCorruption(IOError):
    """Checksum/structure mismatch on the read path (-EIO analog)."""


def _crc32c(data) -> int:
    """Whole-buffer crc32c, raw-register convention (seed 0xFFFFFFFF,
    no final inversion) — shared with the KV plane's seals."""
    b = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    return host_crc32c(b)


# -- wire transaction (de)serialization --------------------------------------
# Full-data form: MStoreOp frames ship entire Transactions between
# daemons (a peer can't dereference our device offsets). The metadata
# plane uses TinDB batches; the meta-op codec below survives only for
# legacy (pre-KV) store migration.

def _encode_op(e: Encoder, op: tuple) -> None:
    kind = op[0]
    e.string(kind)
    if kind in ("mkcoll", "rmcoll"):
        e.string(op[1])
    elif kind in ("touch", "remove", "omap_clear"):
        e.string(op[1]).string(op[2])
    elif kind in ("write", "xor"):
        # data by REFERENCE (no tobytes copy): the buffer rides the
        # encoder's segment list; wire callers keep it alive/unmodified
        # until the frame is acked (the bufferlist aliasing contract),
        # WAL callers join immediately via bytes()
        import numpy as _np
        data = _np.ascontiguousarray(op[4], _np.uint8)
        e.string(op[1]).string(op[2]).u64(op[3]) \
            .blob_ref(memoryview(data).cast("B"))
    elif kind == "truncate":
        e.string(op[1]).string(op[2]).u64(op[3])
    elif kind == "setattr":
        e.string(op[1]).string(op[2]).string(op[3]).blob(op[4])
    elif kind == "rmattr":
        e.string(op[1]).string(op[2]).string(op[3])
    elif kind == "omap_set":
        e.string(op[1]).string(op[2])
        e.mapping(op[3], Encoder.blob, Encoder.blob)
    elif kind == "omap_rmkeys":
        e.string(op[1]).string(op[2])
        e.list(op[3], Encoder.blob)
    else:
        raise EncodingError(f"unknown op {kind!r}")


def _decode_op(d: Decoder) -> tuple:
    kind = d.string()
    if kind in ("mkcoll", "rmcoll"):
        return (kind, d.string())
    if kind in ("touch", "remove", "omap_clear"):
        return (kind, d.string(), d.string())
    if kind in ("write", "xor"):
        cid, oid, off = d.string(), d.string(), d.u64()
        # d.blob() already copied the bytes out of the frame; the op
        # tuple owns them exclusively, so wrapping without a second
        # .copy() is safe (read-only array — stores only read op data)
        data = np.frombuffer(d.blob(), dtype=np.uint8)
        return (kind, cid, oid, off, data)
    if kind == "truncate":
        return (kind, d.string(), d.string(), d.u64())
    if kind == "setattr":
        return (kind, d.string(), d.string(), d.string(), d.blob())
    if kind == "rmattr":
        return (kind, d.string(), d.string(), d.string())
    if kind == "omap_set":
        return (kind, d.string(), d.string(),
                d.mapping(Decoder.blob, Decoder.blob))
    if kind == "omap_rmkeys":
        return (kind, d.string(), d.string(), d.list(Decoder.blob))
    raise EncodingError(f"unknown op {kind!r}")


def _encode_txn(txn: Transaction) -> bytes:
    e = Encoder()
    e.start(1, 1)
    e.list(txn.ops, _encode_op)
    e.finish()
    return e.bytes()


def _encode_txn_iov(txn: Transaction) -> list:
    """Segment-list form for the wire path: shard data buffers
    travel by reference from the transaction straight through
    MStoreOp framing to sendmsg — zero payload copies."""
    e = Encoder()
    e.start(1, 1)
    e.list(txn.ops, _encode_op)
    e.finish()
    return e.segments()


def _decode_txn(body: bytes) -> Transaction:
    d = Decoder(body)
    d.start(1)
    txn = Transaction()
    txn.ops = d.list(_decode_op)
    d.finish()
    return txn


# -- LEGACY metadata-op (de)serialization -------------------------------------
# The pre-KV TinStore WAL carried these records; the codec survives so
# mount() can forward-replay old stores into the KV plane (and so the
# tests can fabricate legacy stores to prove that path).

def _encode_meta_op(e: Encoder, op: tuple) -> None:
    kind = op[0]
    if kind == "setext":
        e.string(kind)
        e.string(op[1]).string(op[2])
        e.u64(op[3]).u64(op[4]).u64(op[5]).u32(op[6])
    elif kind == "setextc":
        # compressed extent: a DISTINCT kind (not extra fields on
        # setext) so stores written before compression existed replay
        # unchanged
        e.string(kind)
        e.string(op[1]).string(op[2])
        e.u64(op[3]).u64(op[4]).u64(op[5]).u32(op[6])
        e.string(op[7]).u64(op[8]).u32(op[9])
    else:
        _encode_op(e, op)


def _decode_meta_op(d: Decoder) -> tuple:
    kind = d.string()
    if kind == "setext":
        return (kind, d.string(), d.string(),
                d.u64(), d.u64(), d.u64(), d.u32())
    if kind == "setextc":
        return (kind, d.string(), d.string(),
                d.u64(), d.u64(), d.u64(), d.u32(),
                d.string(), d.u64(), d.u32())
    if kind in ("mkcoll", "rmcoll"):
        return (kind, d.string())
    if kind in ("touch", "remove", "omap_clear"):
        return (kind, d.string(), d.string())
    if kind == "setattr":
        return (kind, d.string(), d.string(), d.string(), d.blob())
    if kind == "rmattr":
        return (kind, d.string(), d.string(), d.string())
    if kind == "omap_set":
        return (kind, d.string(), d.string(),
                d.mapping(Decoder.blob, Decoder.blob))
    if kind == "omap_rmkeys":
        return (kind, d.string(), d.string(), d.list(Decoder.blob))
    raise EncodingError(f"unknown meta op {kind!r}")


def _encode_meta_txn(ops: list[tuple]) -> bytes:
    e = Encoder()
    e.start(1, 1)
    e.list(ops, _encode_meta_op)
    e.finish()
    return e.bytes()


def _decode_meta_txn(body: bytes) -> list[tuple]:
    d = Decoder(body)
    d.start(1)
    ops = d.list(_decode_meta_op)
    d.finish()
    return ops


# -- block plane --------------------------------------------------------------

class ExtentAllocator:
    """First-fit free-extent list over the flat data device, 4 KiB
    allocation units, coalescing frees (ref: src/os/bluestore/
    AvlAllocator.cc behaviorally; the freelist is derived, not
    persisted — mount/fsck rebuild it from the live extent map)."""

    def __init__(self, device_size: int = 0):
        self.device_size = int(device_size)
        self._free: list[list[int]] = (
            [[0, self.device_size]] if self.device_size else [])

    @staticmethod
    def round_up(n: int) -> int:
        return (int(n) + _ALLOC_UNIT - 1) // _ALLOC_UNIT * _ALLOC_UNIT

    def used_bytes(self) -> int:
        return self.device_size - sum(ln for _, ln in self._free)

    def reserve(self, off: int, length: int) -> None:
        """Mark [off, off+length) used (mount derivation). Raises
        TinStoreCorruption if any part is not free — that's an extent
        overlap or out-of-device reference in the metadata."""
        if length <= 0:
            return
        end = off + length
        if off < 0 or end > self.device_size:
            raise TinStoreCorruption(
                f"extent [{off},{end}) outside device "
                f"(size {self.device_size})")
        for i, (foff, flen) in enumerate(self._free):
            fend = foff + flen
            if foff <= off and end <= fend:
                repl = []
                if foff < off:
                    repl.append([foff, off - foff])
                if end < fend:
                    repl.append([end, fend - end])
                self._free[i:i + 1] = repl
                return
        raise TinStoreCorruption(
            f"extent [{off},{end}) overlaps another allocation")

    def alloc(self, nbytes: int) -> tuple[int, int]:
        """Return (doff, dlen) with dlen = round_up(nbytes). Grows the
        device (caller must ftruncate to self.device_size after).
        Zero bytes need no extent: empty objects must not pin units."""
        if nbytes <= 0:
            return 0, 0
        need = self.round_up(nbytes)
        for i, (foff, flen) in enumerate(self._free):
            if flen >= need:
                if flen == need:
                    del self._free[i]
                else:
                    self._free[i] = [foff + need, flen - need]
                return foff, need
        doff = self.device_size
        self.device_size += need
        return doff, need

    def free(self, off: int, length: int) -> None:
        if length <= 0:
            return
        # insert sorted, coalesce neighbors
        import bisect
        idx = bisect.bisect_left(self._free, [off, length])
        self._free.insert(idx, [off, length])
        merged = []
        for seg in self._free:
            if merged and merged[-1][0] + merged[-1][1] >= seg[0]:
                merged[-1][1] = max(merged[-1][1],
                                    seg[0] + seg[1] - merged[-1][0])
            else:
                merged.append(seg)
        self._free = merged


class _BufferCache:
    """LRU byte cache with a hard budget — the bounded serving plane.
    Objects larger than the whole budget bypass the cache."""

    def __init__(self, budget: int):
        self.budget = int(budget)
        self.total = 0
        self.hits = 0
        self.misses = 0
        self._lru: OrderedDict[tuple, np.ndarray] = OrderedDict()

    def get(self, key) -> np.ndarray | None:
        arr = self._lru.get(key)
        if arr is None:
            self.misses += 1
            return None
        self._lru.move_to_end(key)
        self.hits += 1
        return arr

    def put(self, key, arr: np.ndarray) -> None:
        self.drop(key)
        if arr.nbytes > self.budget:
            return
        self._lru[key] = arr
        self.total += arr.nbytes
        while self.total > self.budget and self._lru:
            _, old = self._lru.popitem(last=False)
            self.total -= old.nbytes

    def drop(self, key) -> None:
        old = self._lru.pop(key, None)
        if old is not None:
            self.total -= old.nbytes

    def drop_coll(self, cid: str) -> None:
        for key in [k for k in self._lru if k[0] == cid]:
            self.drop(key)

    def clear(self) -> None:
        self._lru.clear()
        self.total = 0


class _TinObject:
    """RAM-mirror record: where the bytes live, how big, their crc.
    Compressed blobs (calg != "") additionally carry the STORED
    length (clen) and a crc over the stored bytes (ccrc) — the
    BlueStore per-blob compressed_length + csum-on-stored-data pair;
    `crc` is always over the LOGICAL bytes. Omap is NOT mirrored —
    it lives only in the KV plane; `has_omap` is a write-path hint
    (True may be stale after rmkeys/clear; False is always exact)."""

    __slots__ = ("size", "doff", "dlen", "crc", "xattrs",
                 "calg", "clen", "ccrc", "has_omap")

    def __init__(self, size=0, doff=0, dlen=0, crc=0,
                 xattrs=None, calg="", clen=0, ccrc=0,
                 has_omap=False):
        self.size, self.doff, self.dlen, self.crc = size, doff, dlen, crc
        self.xattrs: dict[str, bytes] = xattrs if xattrs is not None else {}
        self.calg, self.clen, self.ccrc = calg, clen, ccrc
        self.has_omap = has_omap

    @property
    def stored_len(self) -> int:
        return self.clen if self.calg else self.size

    def copy(self) -> "_TinObject":
        return _TinObject(self.size, self.doff, self.dlen, self.crc,
                          dict(self.xattrs), self.calg, self.clen,
                          self.ccrc, self.has_omap)


def _encode_obj(o: _TinObject) -> bytes:
    """The "O" KV record (versioned like every on-disk structure)."""
    e = Encoder()
    e.start(_OBJ_VERSION, _OBJ_VERSION)
    e.u64(o.size).u64(o.doff).u64(o.dlen).u32(o.crc)
    e.string(o.calg).u64(o.clen).u32(o.ccrc)
    e.mapping(o.xattrs, Encoder.string, Encoder.blob)
    e.finish()
    return e.bytes()


def _decode_obj(b: bytes) -> _TinObject:
    d = Decoder(b)
    d.start(_OBJ_VERSION)
    size, doff, dlen, crc = d.u64(), d.u64(), d.u64(), d.u32()
    calg, clen, ccrc = d.string(), d.u64(), d.u32()
    xattrs = d.mapping(Decoder.string, Decoder.blob)
    d.finish()
    return _TinObject(size, doff, dlen, crc, xattrs, calg, clen, ccrc)


def _okey(cid: str, oid: str) -> bytes:
    return cid.encode() + b"\x00" + oid.encode()


def _mkey(cid: str, oid: str, key: bytes) -> bytes:
    return cid.encode() + b"\x00" + oid.encode() + b"\x00" + bytes(key)


# -- collections view (test/scrub poke surface) -------------------------------

class _OmapView(Mapping):
    """Ordered read view of one object's omap, served straight from
    the KV plane's prefix-bounded iterator (keys ascend)."""

    __slots__ = ("_st", "_cid", "_oid")

    def __init__(self, st: "TinStore", cid: str, oid: str):
        self._st, self._cid, self._oid = st, cid, oid

    def __getitem__(self, key: bytes) -> bytes:
        v = self._st._db.get("M", _mkey(self._cid, self._oid, key))
        if v is None:
            raise KeyError(key)
        return v

    def __iter__(self):
        pre = _okey(self._cid, self._oid) + b"\x00"
        for k, _v in self._st._db.iterate(
                "M", start=pre, end=pre[:-1] + b"\x01"):
            yield k[len(pre):]

    def items(self):
        pre = _okey(self._cid, self._oid) + b"\x00"
        for k, v in self._st._db.iterate(
                "M", start=pre, end=pre[:-1] + b"\x01"):
            yield k[len(pre):], v

    def __len__(self):
        return sum(1 for _ in self)


class _ObjProxy:
    """MemStore-_Object-shaped view of one object. `.data` is a
    writable memmap straight onto the device extent: in-place pokes
    are genuine on-disk corruption (no WAL, no crc update); the cache
    entry is invalidated so the next read sees the damage."""

    __slots__ = ("_st", "_cid", "_oid")

    def __init__(self, st: "TinStore", cid: str, oid: str):
        self._st, self._cid, self._oid = st, cid, oid

    def _meta(self) -> _TinObject:
        return self._st._alive()[self._cid][self._oid]

    @property
    def data(self) -> np.ndarray:
        o = self._meta()
        self._st._cache.drop((self._cid, self._oid))
        if o.size == 0:
            return np.zeros(0, dtype=np.uint8)
        # the STORED bytes (compressed blobs expose the compressed
        # stream): pokes are device-plane damage either way, caught
        # by ccrc (compressed) or crc (raw) on the next read
        return np.memmap(self._st._dev_path, dtype=np.uint8, mode="r+",
                         offset=o.doff, shape=(o.stored_len,))

    @property
    def xattrs(self) -> dict[str, bytes]:
        return self._meta().xattrs

    @property
    def omap(self) -> _OmapView:
        self._meta()                 # KeyError propagates
        return _OmapView(self._st, self._cid, self._oid)


class _CollView(Mapping):
    def __init__(self, st: "TinStore", cid: str):
        self._st, self._cid = st, cid

    def _coll(self):
        return self._st._alive()[self._cid]

    def __getitem__(self, oid: str) -> _ObjProxy:
        self._coll()[oid]            # KeyError propagates
        return _ObjProxy(self._st, self._cid, oid)

    def __iter__(self):
        return iter(self._coll())

    def __len__(self):
        return len(self._coll())


class _CollectionsView(Mapping):
    def __init__(self, st: "TinStore"):
        self._st = st

    def __getitem__(self, cid: str) -> _CollView:
        self._st._alive()[cid]       # KeyError propagates
        return _CollView(self._st, cid)

    def __iter__(self):
        return iter(self._st._alive())

    def __len__(self):
        return len(self._st._alive())


# -- the store ----------------------------------------------------------------

class TinStore:
    """File-backed ObjectStore: block-plane data device + extent
    allocator, TinDB ordered-KV metadata plane (WAL + segments +
    manifest), bounded LRU buffer cache, crc32c verify-on-read.
    Interface == MemStore."""

    COMPRESSION_ALGS = ("zlib", "lzma")

    def __init__(self, path: str, o_dsync: bool = False,
                 verify_reads: bool = True,
                 wal_max_bytes: int = 64 << 20,
                 cache_bytes: int = 64 << 20,
                 kv_memtable_bytes: int = 4 << 20,
                 kv_fanout: int = 4,
                 compression: str | None = None,
                 compression_min_blob: int = 4096,
                 compression_required_ratio: float = 0.875,
                 capacity_bytes: int = 0):
        if compression is not None \
                and compression not in self.COMPRESSION_ALGS:
            raise ValueError(f"unknown compression {compression!r}; "
                             f"use one of {self.COMPRESSION_ALGS}")
        self.path = path
        self.o_dsync = o_dsync
        self.verify_reads = verify_reads
        self.wal_max_bytes = wal_max_bytes
        self.cache_bytes = cache_bytes
        self.kv_memtable_bytes = kv_memtable_bytes
        self.kv_fanout = kv_fanout
        # inline compression (ref: BlueStore _do_write compression
        # decision: bluestore_compression_{algorithm,min_blob_size,
        # required_ratio}): blobs >= min_blob that shrink to at most
        # required_ratio of raw are stored compressed; everything
        # else stays raw. Reads are transparent either way.
        self.compression = compression
        self.compression_min_blob = compression_min_blob
        self.compression_required_ratio = compression_required_ratio
        self.compress_stats = {"compressed_blobs": 0, "raw_blobs": 0,
                               "logical_bytes": 0, "stored_bytes": 0}
        self._lock = threading.RLock()
        self._meta: dict[str, dict[str, _TinObject]] | None = None
        self._alloc = ExtentAllocator()
        self._cache = _BufferCache(cache_bytes)
        self._db: TinDB | None = None
        self._dev_fd: int | None = None
        self.committed_txns = 0
        #: capacity ceiling in bytes over device extents + WAL; 0 =
        #: unbounded. Live-shrinkable (set_capacity) for the r21
        #: disk_full injection path — enforcement is in _stage, BEFORE
        #: the allocator grows the device.
        self.capacity_bytes = int(capacity_bytes)
        #: deterministic ENOSPC injection: fn(point) raised-from at
        #: "txn.apply" (here) and every TinDB hook point (wal.append,
        #: flush.*, compact.*) — survives remounts (rewired in mount)
        self._fault = None
        os.makedirs(path, exist_ok=True)
        self.mount()

    # -- paths ---------------------------------------------------------------

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.path, "wal.log")

    @property
    def _ckpt_path(self) -> str:
        """LEGACY (pre-KV) checkpoint path — only read for migration."""
        return os.path.join(self.path, "ckpt")

    @property
    def _dev_path(self) -> str:
        return os.path.join(self.path, "block.dev")

    # -- lifecycle -----------------------------------------------------------

    @staticmethod
    def _is_legacy(path: str) -> bool:
        """Pre-KV layout: no MANIFEST, but a checkpoint and/or WAL
        already exists (a fresh empty directory is NOT legacy)."""
        if os.path.exists(os.path.join(path, "MANIFEST")):
            return False
        if os.path.exists(os.path.join(path, "ckpt")):
            return True
        wal = os.path.join(path, "wal.log")
        try:
            return os.path.getsize(wal) > 0
        except OSError:
            return False

    def mount(self) -> None:
        """Mount the KV metadata plane (migrating a legacy store
        forward first), rebuild the RAM mirror, derive the allocator
        from the surviving extent map, open the device."""
        with self._lock:
            self._cache = _BufferCache(self.cache_bytes)
            self._dev_fd = os.open(self._dev_path,
                                   os.O_RDWR | os.O_CREAT, 0o644)
            try:
                if self._is_legacy(self.path):
                    self._migrate_legacy()
                try:
                    self._db = TinDB(
                        self.path, o_dsync=self.o_dsync,
                        memtable_max_bytes=self.kv_memtable_bytes,
                        fanout=self.kv_fanout, wal_name="wal.log")
                except TinDBCorruption as e:
                    raise TinStoreCorruption(str(e)) from None
                # fault hook survives remounts: each mount builds a
                # fresh TinDB, so the injection fn must be rewired or
                # a revive would silently disarm the chaos stream
                self._db._fault = getattr(self, "_fault", None)
                self._meta = {}
                self._load_mirror()
                self._derive_allocator()
            except Exception:
                os.close(self._dev_fd)
                self._dev_fd = None
                self._meta = None
                raise

    def _load_mirror(self) -> None:
        """RAM mirror (collections + object records + has_omap hints)
        rebuilt from the KV plane — O(metadata), the onode-cache warm
        load. Omap VALUES stay in the DB."""
        meta = self._meta
        for k, _v in self._db.iterate("C"):
            meta.setdefault(k.decode(), {})
        for k, v in self._db.iterate("O"):
            cid_b, oid_b = k.split(b"\x00", 1)
            try:
                obj = _decode_obj(v)
            except EncodingError as e:
                raise TinStoreCorruption(
                    f"bad object record {k!r}: {e}") from None
            meta.setdefault(cid_b.decode(), {})[oid_b.decode()] = obj
        for k, _v in self._db.iterate("M"):
            cid_b, oid_b, _mk = k.split(b"\x00", 2)
            o = meta.get(cid_b.decode(), {}).get(oid_b.decode())
            if o is not None:
                o.has_omap = True
        cnt = self._db.get("S", b"committed_txns")
        self.committed_txns = (struct.unpack("<Q", cnt)[0]
                               if cnt is not None else 0)

    def _derive_allocator(self) -> None:
        dev_size = os.fstat(self._dev_fd).st_size
        # metadata may reference past a file whose tail grow raced a
        # crash — impossible forward (grow precedes WAL append), so a
        # larger-than-file reference is corruption; reserve() raises.
        span = ExtentAllocator.round_up(dev_size)
        alloc = ExtentAllocator(span)
        for coll in self._meta.values():
            for o in coll.values():
                if o.dlen:
                    alloc.reserve(o.doff, o.dlen)
        if span > dev_size:
            os.ftruncate(self._dev_fd, span)
        self._alloc = alloc

    @property
    def is_down(self) -> bool:
        """True between crash()/umount() and the next (re)mount()."""
        return self._meta is None

    def crash(self) -> None:
        """SIGKILL semantics: drop RAM state and handles, NO flush.
        Only bytes already written to the files survive."""
        with self._lock:
            if self._db is not None:
                self._db.crash()
            if self._dev_fd is not None:
                try:
                    os.close(self._dev_fd)
                except OSError:
                    pass
                self._dev_fd = None
            self._meta = None
            self._cache.clear()

    def remount(self) -> None:
        """Restart after crash(): recover purely from disk."""
        self.mount()

    def umount(self) -> None:
        """Clean shutdown: flush the memtable then release handles."""
        with self._lock:
            self._alive()
            self._db.umount()
            os.close(self._dev_fd)
            self._dev_fd = None
            self._meta = None
            self._cache.clear()

    def _alive(self) -> dict[str, dict[str, _TinObject]]:
        if self._meta is None:
            raise RuntimeError(f"TinStore {self.path} is down "
                               f"(crashed/umounted; remount() first)")
        return self._meta

    # -- capacity (r21 capacity plane; contract shared w/ MemStore) ----------

    def set_capacity(self, nbytes: int) -> None:
        """Live capacity change; shrinking below current usage makes
        the ratio read > 1.0 and every staging alloc ENOSPC — the
        disk_full fault stream's lever."""
        with self._lock:
            self.capacity_bytes = int(nbytes)

    def set_fault(self, fn) -> None:
        """Install the deterministic injection hook on the store AND
        its KV plane (wal.append / flush.* / compact.* points)."""
        with self._lock:
            self._fault = fn
            if self._db is not None:
                self._db._fault = fn

    def used_bytes(self) -> int:
        """Allocated device extents + unflushed WAL — what counts
        against capacity. Sealed KV segments are deliberately excluded
        (they are O(metadata), bounded by compaction; documented in
        ARCHITECTURE's capacity-plane section)."""
        with self._lock:
            used = self._alloc.used_bytes()
            if self._db is not None and not self._db.is_down:
                used += self._db.wal_size()
            return used

    def statfs(self) -> dict:
        """Bytes total/used/avail (ObjectStore::statfs). total == 0
        means unbounded: the mon ladder never computes a ratio."""
        used = self.used_bytes()
        total = int(self.capacity_bytes)
        return {"total": total, "used": used,
                "avail": max(0, total - used) if total else 0}

    # -- legacy (pre-KV) store migration -------------------------------------

    def _migrate_legacy(self) -> None:
        """Forward replay: legacy ckpt + meta-op WAL -> one TinDB
        segment + MANIFEST with covered_seq past every legacy record
        (same WAL framing, so the old records are seq-skipped, never
        body-parsed). Crash before the MANIFEST lands = legacy store
        intact, migration re-runs; after = KV store live."""
        colls, omaps, committed, last_seq = \
            self._legacy_load(self.path, truncate_torn=True)
        items: dict[bytes, bytes] = {
            b"S\x00committed_txns": struct.pack("<Q", committed)}
        for cid, coll in colls.items():
            items[b"C\x00" + cid.encode()] = b""
            for oid, o in coll.items():
                items[b"O\x00" + _okey(cid, oid)] = _encode_obj(o)
        for (cid, oid), om in omaps.items():
            for k, v in om.items():
                items[b"M\x00" + _mkey(cid, oid, k)] = v
        seg_path = os.path.join(self.path, "seg-00000001.tdb")
        write_segment(seg_path, ((k, items[k]) for k in sorted(items)))
        db = TinDB(self.path, wal_name="wal.log", mount=False)
        db._covered_seq = last_seq
        db._next_seg = 2
        db._levels = [[Segment(seg_path)]]
        db._write_manifest()            # the commit point
        db.crash()
        try:
            os.unlink(self._ckpt_path)  # cosmetic; ignored once KV
        except OSError:
            pass

    @staticmethod
    def _legacy_load(path: str, truncate_torn: bool):
        """Read a pre-KV store's state: (collections, omaps,
        committed_txns, last_wal_seq). Raises TinStoreCorruption on
        damage (same contract the legacy mount had)."""
        colls: dict[str, dict[str, _TinObject]] = {}
        omaps: dict[tuple[str, str], dict[bytes, bytes]] = {}
        committed = 0
        base_seq = 0
        ckpt = os.path.join(path, "ckpt")
        try:
            with open(ckpt, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            raw = None
        if raw is not None:
            if len(raw) < 4:
                raise TinStoreCorruption(f"{ckpt}: truncated")
            (crc,) = struct.unpack_from("<I", raw, len(raw) - 4)
            if host_crc32c(raw[:-4]) != crc:
                raise TinStoreCorruption(f"{ckpt}: file seal "
                                         f"crc mismatch")
            d = Decoder(raw[:-4])
            try:
                v = d.start(_CKPT_VERSION)
                base_seq = d.u64()
                committed = d.u64()
                for _ in range(d.u32()):
                    cid = d.string()
                    coll = colls.setdefault(cid, {})
                    for _ in range(d.u32()):
                        oid = d.string()
                        size, doff, dlen, ocrc = (d.u64(), d.u64(),
                                                  d.u64(), d.u32())
                        xattrs = d.mapping(Decoder.string, Decoder.blob)
                        omap = d.mapping(Decoder.blob, Decoder.blob)
                        if v >= 3:
                            calg, clen, ccrc = (d.string(), d.u64(),
                                                d.u32())
                        else:
                            calg, clen, ccrc = "", 0, 0
                        coll[oid] = _TinObject(size, doff, dlen, ocrc,
                                               xattrs, calg, clen, ccrc)
                        if omap:
                            omaps[(cid, oid)] = omap
                d.finish()
            except EncodingError as e:
                raise TinStoreCorruption(f"{ckpt}: {e}") from None
        wal_path = os.path.join(path, "wal.log")
        seq = base_seq
        gen = scan_wal(wal_path)
        while True:
            try:
                rseq, body = next(gen)
            except StopIteration as stop:
                good_bytes, torn, err = stop.value
                if err:
                    raise TinStoreCorruption(
                        f"{wal_path}: {err} (mid-log corruption; "
                        f"run fsck)")
                if torn and truncate_torn:
                    with open(wal_path, "ab") as f:
                        f.truncate(good_bytes)
                break
            if rseq <= base_seq:
                continue                     # checkpoint covers it
            if rseq != seq + 1:
                raise TinStoreCorruption(
                    f"{wal_path}: seq jump {seq} -> {rseq}")
            try:
                ops = _decode_meta_txn(body)
            except EncodingError as e:
                raise TinStoreCorruption(
                    f"{wal_path}: record {rseq}: {e}") from None
            for op in ops:
                TinStore._legacy_apply(colls, omaps, op)
            committed += 1
            seq = rseq
        return colls, omaps, committed, seq

    @staticmethod
    def _legacy_apply(colls, omaps, op: tuple) -> None:
        kind = op[0]
        if kind == "mkcoll":
            colls.setdefault(op[1], {})
        elif kind == "rmcoll":
            coll = colls.pop(op[1], {})
            for oid in coll:
                omaps.pop((op[1], oid), None)
        elif kind == "touch":
            colls[op[1]].setdefault(op[2], _TinObject())
        elif kind in ("setext", "setextc"):
            _, cid, oid, doff, dlen, size, crc = op[:7]
            o = colls[cid].setdefault(oid, _TinObject())
            o.doff, o.dlen, o.size, o.crc = doff, dlen, size, crc
            if kind == "setextc":
                o.calg, o.clen, o.ccrc = op[7], op[8], op[9]
            else:
                o.calg, o.clen, o.ccrc = "", 0, 0
        elif kind == "remove":
            colls[op[1]].pop(op[2], None)
            omaps.pop((op[1], op[2]), None)
        elif kind == "setattr":
            colls[op[1]].setdefault(op[2], _TinObject()) \
                .xattrs[op[3]] = op[4]
        elif kind == "rmattr":
            o = colls[op[1]].get(op[2])
            if o is not None:
                o.xattrs.pop(op[3], None)
        elif kind == "omap_set":
            colls[op[1]].setdefault(op[2], _TinObject())
            omaps.setdefault((op[1], op[2]), {}).update(op[3])
        elif kind == "omap_rmkeys":
            om = omaps.get((op[1], op[2]))
            if om is not None:
                for k in op[3]:
                    om.pop(k, None)
        elif kind == "omap_clear":
            omaps.pop((op[1], op[2]), None)
        else:
            raise TinStoreCorruption(f"unknown legacy meta op {kind!r}")

    # -- flush (the checkpoint role) -----------------------------------------

    def checkpoint(self) -> None:
        """Flush the KV memtable to a sorted segment and reset the
        WAL (the metadata-checkpoint role; cost O(memtable))."""
        with self._lock:
            self._alive()
            self._db.flush()

    # -- transactional write path -------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            self._alive()
            self._validate(txn)
            if self._fault is not None:
                # injection point BEFORE any staging: an injected
                # ENOSPC aborts with nothing allocated or written
                self._fault("txn.apply")
            staged: dict[tuple[str, str], np.ndarray] = {}
            # objects removed EARLIER IN THIS TXN: a later write must
            # start from empty, not resurrect the pre-txn bytes
            # (MemStore applies ops in order; staging must match)
            gone: set[tuple[str, str]] = set()
            gone_colls: set[str] = set()
            new_extents: list[tuple[int, int]] = []
            meta_ops: list[tuple] = []
            try:
                for op in txn.ops:
                    kind = op[0]
                    if kind == "remove":
                        gone.add((op[1], op[2]))
                        staged.pop((op[1], op[2]), None)
                    elif kind == "rmcoll":
                        # stays in gone_colls even if re-created later
                        # in the txn: the fresh collection is EMPTY,
                        # pre-txn objects must not show through it
                        gone_colls.add(op[1])
                        for key in [k for k in staged if k[0] == op[1]]:
                            del staged[key]
                    if kind in ("write", "xor"):
                        _, cid, oid, woff, data = op
                        cur = self._staged_bytes(staged, gone,
                                                 gone_colls, cid, oid)
                        end = woff + len(data)
                        if end > len(cur):
                            grown = np.zeros(end, dtype=np.uint8)
                            grown[:len(cur)] = cur
                            cur = grown
                        else:
                            cur = cur.copy()
                        if kind == "xor":
                            cur[woff:end] ^= data
                        else:
                            cur[woff:end] = data
                        meta_ops.append(self._stage(
                            staged, new_extents, cid, oid, cur))
                    elif kind == "truncate":
                        _, cid, oid, size = op
                        cur = self._staged_bytes(staged, gone,
                                                 gone_colls, cid, oid)
                        if size <= len(cur):
                            cur = cur[:size].copy()
                        else:
                            grown = np.zeros(size, dtype=np.uint8)
                            grown[:len(cur)] = cur
                            cur = grown
                        meta_ops.append(self._stage(
                            staged, new_extents, cid, oid, cur))
                    else:
                        meta_ops.append(op)
            except Exception:
                for doff, dlen in new_extents:
                    self._alloc.free(doff, dlen)
                raise
            if self.o_dsync and new_extents:
                os.fsync(self._dev_fd)     # data durable BEFORE the WAL
            try:
                self._db.submit_transaction(self._kv_txn_for(meta_ops))
            except OSError:
                # ENOSPC on the WAL append (r21): the KV plane rolled
                # its seq/tail back and nothing references the staged
                # extents — free them so the abort is atomic live,
                # not just after a remount re-derives the allocator
                for doff, dlen in new_extents:
                    self._alloc.free(doff, dlen)
                raise
            for op in meta_ops:
                self._apply_meta(op)
            for key, arr in staged.items():
                cid, oid = key
                if cid in self._meta and oid in self._meta[cid]:
                    self._cache.put(key, arr)
            self.committed_txns += 1
            if self._db.wal_size() >= self.wal_max_bytes:
                try:
                    self._db.flush()
                except OSError:
                    # ENOSPC (real or injected) on the post-commit
                    # flush: the txn above already committed — the
                    # memtable/WAL stay whole and the next txn retries
                    # the flush once space returns
                    pass

    def _kv_txn_for(self, meta_ops: list[tuple]):
        """Translate one metadata-op batch into ONE TinDB transaction
        (the BlueStore txc->t WriteBatch build). Object records are
        re-encoded whole per touch (they're small — extent refs +
        xattrs); omap entries map 1:1 onto "M" keys; range deletes
        cover collection/object teardown."""
        kvt = self._db.transaction()
        kvt.set("S", b"committed_txns",
                struct.pack("<Q", self.committed_txns + 1))
        work: dict[tuple[str, str], _TinObject | None] = {}

        def getobj(cid, oid, create):
            key = (cid, oid)
            if key in work:
                o = work[key]
            else:
                cur = self._meta.get(cid, {}).get(oid)
                o = cur.copy() if cur is not None else None
            if o is None and create:
                o = _TinObject()
            work[key] = o
            return o

        def put(cid, oid, o):
            kvt.set("O", _okey(cid, oid), _encode_obj(o))

        for op in meta_ops:
            kind = op[0]
            if kind == "mkcoll":
                kvt.set("C", op[1].encode(), b"")
            elif kind == "rmcoll":
                cid = op[1]
                kvt.rmkey("C", cid.encode())
                kvt.rmkeys_by_prefix("O", cid.encode() + b"\x00")
                kvt.rmkeys_by_prefix("M", cid.encode() + b"\x00")
                for key in [k for k in work if k[0] == cid]:
                    work[key] = None
            elif kind == "touch":
                _, cid, oid = op
                put(cid, oid, getobj(cid, oid, create=True))
            elif kind in ("setext", "setextc"):
                _, cid, oid, doff, dlen, size, crc = op[:7]
                o = getobj(cid, oid, create=True)
                o.doff, o.dlen, o.size, o.crc = doff, dlen, size, crc
                if kind == "setextc":
                    o.calg, o.clen, o.ccrc = op[7], op[8], op[9]
                else:
                    o.calg, o.clen, o.ccrc = "", 0, 0
                put(cid, oid, o)
            elif kind == "remove":
                _, cid, oid = op
                prior = getobj(cid, oid, create=False)
                work[(cid, oid)] = None
                kvt.rmkey("O", _okey(cid, oid))
                if prior is not None and prior.has_omap:
                    kvt.rmkeys_by_prefix(
                        "M", _okey(cid, oid) + b"\x00")
            elif kind == "setattr":
                _, cid, oid, k, v = op
                o = getobj(cid, oid, create=True)
                o.xattrs[k] = v
                put(cid, oid, o)
            elif kind == "rmattr":
                _, cid, oid, k = op
                o = getobj(cid, oid, create=False)
                if o is not None:
                    o.xattrs.pop(k, None)
                    put(cid, oid, o)
            elif kind == "omap_set":
                _, cid, oid, kv = op
                o = getobj(cid, oid, create=True)
                if not o.has_omap:
                    o.has_omap = True
                put(cid, oid, o)
                for k, v in kv.items():
                    kvt.set("M", _mkey(cid, oid, k), v)
            elif kind == "omap_rmkeys":
                _, cid, oid, keys = op
                if getobj(cid, oid, create=False) is not None:
                    for k in keys:
                        kvt.rmkey("M", _mkey(cid, oid, k))
            elif kind == "omap_clear":
                _, cid, oid = op
                o = getobj(cid, oid, create=False)
                if o is not None and o.has_omap:
                    kvt.rmkeys_by_prefix(
                        "M", _okey(cid, oid) + b"\x00")
            else:
                raise ValueError(f"unknown meta op {kind!r}")
        return kvt

    def _staged_bytes(self, staged, gone, gone_colls,
                      cid, oid) -> np.ndarray:
        key = (cid, oid)
        if key in staged:
            return staged[key]
        if key in gone or cid in gone_colls:
            return np.zeros(0, dtype=np.uint8)
        coll = self._meta.get(cid, {})
        if oid in coll:
            return self._object_bytes(cid, oid)
        return np.zeros(0, dtype=np.uint8)

    @staticmethod
    def _compress(alg: str, raw: bytes) -> bytes:
        if alg == "zlib":
            import zlib
            return zlib.compress(raw, 3)
        import lzma
        return lzma.compress(raw, preset=0)

    @staticmethod
    def _decompress(alg: str, stored: bytes, logical_size: int) -> bytes:
        """Bounded decompress: never inflate past the metadata's
        logical size (a corrupt/bombed blob fails, it doesn't OOM)."""
        if alg == "zlib":
            import zlib
            dec = zlib.decompressobj()
        else:
            import lzma
            dec = lzma.LZMADecompressor()
        out = dec.decompress(stored, logical_size + 1)
        return out

    def _stage(self, staged, new_extents, cid, oid,
               arr: np.ndarray) -> tuple:
        """COW the object's new bytes into a fresh extent; return the
        setext/setextc metadata op. Nothing commits until the KV
        batch. Compression happens HERE (the _do_write decision):
        the device and the crc-on-stored-bytes see compressed data,
        the cache and the logical crc see raw data."""
        stored = arr.tobytes()
        calg = ""
        if self.compression is not None \
                and len(arr) >= self.compression_min_blob:
            comp = self._compress(self.compression, stored)
            if len(comp) <= self.compression_required_ratio * len(arr):
                stored, calg = comp, self.compression
        # capacity gate BEFORE the allocator grows the device: the
        # raise unwinds through queue_transaction's except path, which
        # frees every extent this txn already staged — the ENOSPC
        # abort is atomic (nothing hit the KV plane yet)
        if self.capacity_bytes:
            need = ExtentAllocator.round_up(max(1, len(stored)))
            if self.used_bytes() + need > self.capacity_bytes:
                import errno
                raise OSError(
                    errno.ENOSPC,
                    f"tinstore over capacity "
                    f"({self.capacity_bytes} bytes)")
        doff, dlen = self._alloc.alloc(len(stored))
        if self._alloc.device_size > os.fstat(self._dev_fd).st_size:
            os.ftruncate(self._dev_fd, self._alloc.device_size)
        if stored:
            os.pwrite(self._dev_fd, stored, doff)
        new_extents.append((doff, dlen))
        staged[(cid, oid)] = arr
        st = self.compress_stats
        st["logical_bytes"] += len(arr)
        st["stored_bytes"] += len(stored)
        if calg:
            st["compressed_blobs"] += 1
            return ("setextc", cid, oid, doff, dlen, len(arr),
                    _crc32c(arr), calg, len(stored),
                    _crc32c(np.frombuffer(stored, np.uint8)))
        st["raw_blobs"] += 1
        return ("setext", cid, oid, doff, dlen, len(arr), _crc32c(arr))

    def _validate(self, txn: Transaction) -> None:
        # the ObjectStore contract: ops referencing missing
        # collections are caller bugs -> abort before mutating anything
        cols = set(self._meta)
        for op in txn.ops:
            kind = op[0]
            if kind == "mkcoll":
                cols.add(op[1])
            elif kind == "rmcoll":
                if op[1] not in cols:
                    raise KeyError(f"rmcoll: no collection {op[1]!r}")
                cols.discard(op[1])
            else:
                if op[1] not in cols:
                    raise KeyError(f"{kind}: no collection {op[1]!r}")

    def _apply_meta(self, op: tuple) -> None:
        """Apply one metadata op to the RAM mirror (the KV plane got
        the same mutation in the committed batch); frees replaced
        extents back to the allocator and maintains the cache."""
        meta = self._meta
        kind = op[0]
        if kind == "mkcoll":
            meta.setdefault(op[1], {})
        elif kind == "rmcoll":
            coll = meta.pop(op[1])
            for o in coll.values():
                if o.dlen:
                    self._alloc.free(o.doff, o.dlen)
            self._cache.drop_coll(op[1])
        elif kind == "touch":
            meta[op[1]].setdefault(op[2], _TinObject())
        elif kind in ("setext", "setextc"):
            _, cid, oid, doff, dlen, size, crc = op[:7]
            o = meta[cid].setdefault(oid, _TinObject())
            if o.dlen and (o.doff, o.dlen) != (doff, dlen):
                self._alloc.free(o.doff, o.dlen)
            o.doff, o.dlen, o.size, o.crc = doff, dlen, size, crc
            if kind == "setextc":
                o.calg, o.clen, o.ccrc = op[7], op[8], op[9]
            else:
                o.calg, o.clen, o.ccrc = "", 0, 0
        elif kind == "remove":
            o = meta[op[1]].pop(op[2], None)
            if o is not None and o.dlen:
                self._alloc.free(o.doff, o.dlen)
            self._cache.drop((op[1], op[2]))
        elif kind == "setattr":
            meta[op[1]].setdefault(op[2], _TinObject()) \
                .xattrs[op[3]] = op[4]
        elif kind == "rmattr":
            o = meta[op[1]].get(op[2])
            if o is not None:
                o.xattrs.pop(op[3], None)
        elif kind == "omap_set":
            # keys live in the KV plane; mirror only existence + hint
            o = meta[op[1]].setdefault(op[2], _TinObject())
            o.has_omap = True
        elif kind in ("omap_rmkeys", "omap_clear"):
            pass                             # KV-plane-only mutation
        else:
            raise ValueError(f"unknown meta op {kind!r}")

    # -- reads (bounded cache + verify-on-read) ------------------------------

    def _object_bytes(self, cid: str, oid: str) -> np.ndarray:
        """Full object bytes via the cache; miss = device pread +
        crc verify + insert (LRU eviction keeps the budget)."""
        key = (cid, oid)
        arr = self._cache.get(key)
        o = self._meta[cid][oid]
        if arr is not None and len(arr) == o.size:
            if self.verify_reads:
                self._verify(cid, oid, arr, o.crc)
            return arr
        if o.size == 0:
            return np.zeros(0, dtype=np.uint8)
        raw = os.pread(self._dev_fd, o.stored_len, o.doff)
        if o.calg:
            # verify the STORED bytes first (device-plane damage is
            # caught before the decompressor sees it), then inflate
            # and verify the logical crc
            if self.verify_reads \
                    and _crc32c(np.frombuffer(raw, np.uint8)) != o.ccrc:
                raise TinStoreCorruption(
                    f"{cid}/{oid}: stored-bytes crc mismatch "
                    f"(compressed blob, verify-on-read)")
            try:
                raw = self._decompress(o.calg, raw, o.size)
            except Exception as e:   # noqa: BLE001 — corrupt stream
                raise TinStoreCorruption(
                    f"{cid}/{oid}: decompress failed: {e}") from None
            if len(raw) != o.size:
                raise TinStoreCorruption(
                    f"{cid}/{oid}: decompressed {len(raw)} bytes, "
                    f"expected {o.size}")
        arr = np.frombuffer(raw, dtype=np.uint8)
        if self.verify_reads:
            self._verify(cid, oid, arr, o.crc)
        self._cache.put(key, arr)
        return arr

    def _verify(self, cid: str, oid: str, arr: np.ndarray,
                want: int) -> None:
        got = _crc32c(arr)
        if got != want:
            raise TinStoreCorruption(
                f"{cid}/{oid}: crc {got:#x} != expected {want:#x} "
                f"(verify-on-read)")

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> np.ndarray:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            data = self._object_bytes(cid, oid)
            if length is None:
                return data[offset:].copy()
            return data[offset:offset + length].copy()

    def stat(self, cid: str, oid: str) -> int:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            return coll[oid].size

    def getattr(self, cid: str, oid: str, key: str) -> bytes:
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            return coll[oid].xattrs[key]

    def exists(self, cid: str, oid: str) -> bool:
        with self._lock:
            meta = self._alive()
            return cid in meta and oid in meta[cid]

    # -- ordered listings (served from the KV plane) -------------------------

    def list_objects(self, cid: str, start_after: str | None = None,
                     limit: int | None = None) -> list[str]:
        """Ordered object listing from the KV plane's prefix-bounded
        iterator. With (start_after, limit) this is a PAGE: cost
        O(page + log segments), independent of collection size — the
        sublinear listing the flat-dict scan couldn't give (ref:
        BlueStore::collection_list's rocksdb iterator walk)."""
        with self._lock:
            if cid not in self._alive():
                return []
            pre = cid.encode() + b"\x00"
            start = pre if start_after is None \
                else pre + start_after.encode() + b"\x00"
            it = self._db.iterate("O", start=start,
                                  end=pre[:-1] + b"\x01")
        out: list[str] = []
        for k, _v in it:
            out.append(k[len(pre):].decode())
            if limit is not None and len(out) >= limit:
                break
        return out

    def list_collections(self) -> list[str]:
        with self._lock:
            self._alive()
            return [k.decode() for k, _v in self._db.iterate("C")]

    def omap_iter(self, cid: str, oid: str,
                  start_after: bytes | None = None,
                  limit: int | None = None) -> list[tuple[bytes, bytes]]:
        """Ordered omap page for one object (the DBObjectMap
        get_iterator role): prefix-bounded, O(page)."""
        with self._lock:
            coll = self._alive().get(cid)
            if coll is None or oid not in coll:
                raise KeyError(f"no object {cid}/{oid}")
            pre = _okey(cid, oid) + b"\x00"
            start = pre if start_after is None \
                else pre + bytes(start_after) + b"\x00"
            it = self._db.iterate("M", start=start,
                                  end=pre[:-1] + b"\x01")
        out: list[tuple[bytes, bytes]] = []
        for k, v in it:
            out.append((k[len(pre):], v))
            if limit is not None and len(out) >= limit:
                break
        return out

    @property
    def collections(self) -> _CollectionsView:
        """MemStore-shaped state access — the tests and scrub paths
        poke objects through this; `.data` mutations write the device
        in place, bypassing the WAL and crc on purpose (that's what
        corruption IS)."""
        self._alive()
        return _CollectionsView(self)

    def cache_stats(self) -> dict:
        return {"budget": self._cache.budget, "bytes": self._cache.total,
                "hits": self._cache.hits, "misses": self._cache.misses}

    def kv_stats(self) -> dict:
        """KV-plane introspection (segment/level/memtable shape)."""
        with self._lock:
            self._alive()
            return {**self._db.segment_stats(), **self._db.stats}

    @property
    def kv_perf(self):
        """The mounted TinDB's declared PerfCounters (None when the
        store is down) — a daemon nests this under "tindb" in its
        perf dump."""
        db = self._db
        return db.perf if db is not None else None

    def compact(self) -> None:
        """Full KV compaction (the ceph-kvstore-tool compact role)."""
        with self._lock:
            self._alive()
            self._db.compact()

    # -- fsck ----------------------------------------------------------------

    @staticmethod
    def fsck(path: str) -> dict:
        """Offline integrity audit (ref: BlueStore::fsck): the KV
        plane (manifest seal, segment seals + ordering, WAL chain via
        TinDB.fsck), KV-vs-block cross-checks (omap rows need an
        object record, object records a collection, extents in-bounds
        and disjoint), and every object's data crc read straight from
        the device — without mutating anything. Legacy (pre-KV)
        stores get the equivalent legacy audit."""
        report = {"objects": 0, "bad_objects": [], "wal_records": 0,
                  "torn_tail": False, "errors": [], "extent_errors": [],
                  "device_bytes": 0, "used_bytes": 0,
                  "format": "kv", "kv": {}, "omap_keys": 0}
        if TinStore._is_legacy(path):
            report["format"] = "legacy"
            try:
                colls, omaps, _committed, _seq = \
                    TinStore._legacy_load(path, truncate_torn=False)
            except TinStoreCorruption as e:
                report["errors"].append(str(e))
                return report
            report["omap_keys"] = sum(len(m) for m in omaps.values())
            TinStore._audit_block_plane(path, colls, report)
            return report
        kv = TinDB.fsck(path)
        report["kv"] = kv
        report["wal_records"] = kv["wal_records"]
        report["torn_tail"] = kv["torn_tail"]
        report["errors"].extend(kv["errors"])
        if kv["errors"]:
            return report
        try:
            snap = TinDB.open_readonly(path)
        except TinDBCorruption as e:
            report["errors"].append(str(e))
            return report
        colls: dict[str, dict[str, _TinObject]] = {}
        for k, _v in snap.iterate("C"):
            colls.setdefault(k.decode(), {})
        for k, v in snap.iterate("O"):
            cid_b, oid_b = k.split(b"\x00", 1)
            cid = cid_b.decode()
            if cid not in colls:
                report["errors"].append(
                    f"object record {cid}/{oid_b.decode()} has no "
                    f"collection record")
                colls.setdefault(cid, {})
            try:
                colls[cid][oid_b.decode()] = _decode_obj(v)
            except EncodingError as e:
                report["errors"].append(f"bad object record {k!r}: {e}")
        for k, _v in snap.iterate("M"):
            cid_b, oid_b, _mk = k.split(b"\x00", 2)
            report["omap_keys"] += 1
            if oid_b.decode() not in colls.get(cid_b.decode(), {}):
                report["errors"].append(
                    f"omap key for missing object "
                    f"{cid_b.decode()}/{oid_b.decode()}")
        TinStore._audit_block_plane(path, colls, report)
        return report

    @staticmethod
    def _audit_block_plane(path: str, colls, report: dict) -> None:
        """Extent + data-crc audit shared by the kv and legacy fsck
        paths: every referenced extent in-bounds and disjoint
        (reserve() raises on violation), every object's stored bytes
        re-checksummed straight from the device."""
        try:
            dev_size = os.path.getsize(os.path.join(path, "block.dev"))
        except OSError:
            dev_size = 0
        audit = ExtentAllocator(ExtentAllocator.round_up(dev_size))
        report["device_bytes"] = dev_size
        try:
            dev_fd = os.open(os.path.join(path, "block.dev"),
                             os.O_RDONLY)
        except OSError:
            dev_fd = None
        try:
            for cid, coll in colls.items():
                for oid, o in coll.items():
                    report["objects"] += 1
                    if o.dlen:
                        try:
                            audit.reserve(o.doff, o.dlen)
                        except TinStoreCorruption as e:
                            report["extent_errors"].append(
                                f"{cid}/{oid}: {e}")
                            continue
                    if o.size and dev_fd is not None:
                        raw = os.pread(dev_fd, o.stored_len, o.doff)
                        sarr = np.frombuffer(raw, np.uint8)
                        if o.calg:
                            # stored-bytes seal first, then inflate
                            # and audit the logical crc too
                            if _crc32c(sarr) != o.ccrc:
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            try:
                                raw = TinStore._decompress(
                                    o.calg, raw, o.size)
                            except Exception:  # noqa: BLE001
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            if len(raw) != o.size:
                                report["bad_objects"].append(
                                    f"{cid}/{oid}")
                                continue
                            sarr = np.frombuffer(raw, np.uint8)
                        if _crc32c(sarr) != o.crc:
                            report["bad_objects"].append(f"{cid}/{oid}")
        finally:
            if dev_fd is not None:
                os.close(dev_fd)
        report["used_bytes"] = audit.used_bytes()
