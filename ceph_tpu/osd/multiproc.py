"""Multi-process OSD scale-out — real OS processes behind the wire.

The GIL is the wall the r13 reactor/shard work cannot move on its
own: one Python process serializes every byte of framing, sealing and
dispatch onto one core no matter how many reactors or op shards it
runs. This module puts each OSD daemon in its OWN process (the
reference's deployment shape — one ceph-osd process per device), so a
multi-core host really runs N OSDs on N cores. Monitors and clients
stay in the orchestrating process; everything between daemons already
travels over real sockets, so nothing in the data plane changes —
only where the processes live.

Mechanics:

* the parent spawns `python -m ceph_tpu.osd.multiproc` per OSD and
  ships ONE json config line over stdin (secrets ride the pipe, never
  argv); the child builds a real OSDDaemon against a config shim that
  answers the same surface StandaloneCluster does;
* the child reports its messenger address on stdout, then serves
  control lines (peer wiring, partitions, injection knobs, boot
  announcements) — the side channel plays the role the test harness's
  direct method calls play in-process;
* `kill` is a REAL SIGKILL: no cooperative shutdown, the process
  vanishes mid-syscall exactly like a crashed ceph-osd. Revive spawns
  a fresh process over the same store directory (TinStore remounts
  its WAL; a MemStore child loses RAM state like real RAM does);
* children share the parent's persistent jit compile cache
  (utils/jax_cache.py) so N cold processes pay ~one compile set, not
  N — the same trick that fixed r09's cold recovery;
* the parent observes children through their admin sockets (bound in
  the cluster's shared admin_dir): `pg clean` drives wait_for_clean,
  `perf dump` feeds bench attribution;
* control-parity lines (r15): `rotate` pushes rotated service secrets
  into the child's in-RAM verifier (rotate_service_secrets now works
  against --osd-procs — secrets cross stdin, never argv), and `fsck`
  runs a quiesced store audit inside the child and answers on stdout
  — the two RAM-reaching helpers the r13 harness documented as
  in-process-only.
"""

from __future__ import annotations

import base64
import json
import os
import subprocess
import sys
import threading
import time


# -- parent side --------------------------------------------------------------

class _ProcStop:
    """threading.Event's is_set() surface for a child process: 'set'
    means the process is gone (killed or crashed)."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self._forced = False

    def is_set(self) -> bool:
        return self._forced or self._proc.poll() is not None

    def set(self) -> None:
        self._forced = True


class _HandleMsgr:
    """The slice of a child's Messenger the cluster harness drives,
    forwarded as control lines: address book updates, partition
    blocks, injection knobs."""

    def __init__(self, handle: "OSDProcHandle"):
        self._h = handle
        self.addr: tuple | None = None   # set at ready
        self.name = handle.name

    def add_peer(self, peer: str, addr) -> None:
        self._h._control({"cmd": "add_peer", "peer": peer,
                          "addr": list(addr)})

    def set_blocked(self, peers) -> None:
        self._h._control({"cmd": "set_blocked",
                          "peers": sorted(peers)})

    def seed_injection(self, seed: int) -> None:
        self._h._control({"cmd": "seed_injection", "seed": int(seed)})

    def set_inject_socket_failures(self, every: int) -> None:
        self._h._control({"cmd": "inject_socket_failures",
                          "every": int(every)})

    def set_inject_delay(self, every: int, max_ms: float) -> None:
        self._h._control({"cmd": "inject_delay", "every": int(every),
                          "max_ms": float(max_ms)})


class OSDProcHandle:
    """Parent-side proxy for one OSD child process. Mimics the
    OSDDaemon attributes the StandaloneCluster harness touches
    (name, _stop, msgr address book, kill/revive); everything else
    goes over the wire or the child's admin socket."""

    def __init__(self, cluster, osd_id: int):
        self.c = cluster
        self.osd_id = osd_id
        self.name = f"osd.{osd_id}"
        self.msgr = _HandleMsgr(self)
        self._ctl_lock = threading.Lock()
        self._spawn()

    # -- lifecycle -----------------------------------------------------------

    def _config(self) -> dict:
        c = self.c
        cfg = {
            "osd_id": self.osd_id,
            "secret": _b64(c.secret),
            "compress": c.compress,
            "profile": c.profile,
            "pg_num": c.pg_num,
            "pool_size": c.pool_size,
            "pool_min_size": c.pool_min_size,
            "is_erasure": c.is_erasure,
            "chunk_size": c.chunk_size,
            "op_timeout": c.op_timeout,
            "hb_interval": c.hb_interval,
            "hb_grace": c.hb_grace,
            "admin_dir": c.admin_dir,
            "store": c.store_kind,
            "store_dir": c.store_dir,
            "op_shards": c.op_shards,
            "msgr_workers": c.msgr_workers,
            "msgr_uds": c.msgr_uds,
            "mon_names": [m.name for m in c.mons] if c.mons else
            [f"mon.{r}" for r in range(3)],
            "osd_ids": list(range(c.n_osds)),
            "jax_cache_dir": os.environ.get("BENCH_JAX_CACHE"),
            "verbose": bool(c.verbose),
        }
        if c.key_server is not None:
            cfg["rotating_osd"] = c.key_server.export_rotating("osd")
            cfg["osd_secret"] = _b64(c.osd_secrets[self.osd_id])
        return cfg

    def _spawn(self) -> None:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.osd.multiproc"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL if not self.c.verbose else None,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            env=env, text=True)
        self._stop = _ProcStop(self._proc)
        self._proc.stdin.write(json.dumps(self._config()) + "\n")
        self._proc.stdin.flush()

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until the child reports its messenger address (jax
        import + store mount happen before it)."""
        t_end = time.monotonic() + timeout
        line = None

        def _read():
            nonlocal line
            line = self._proc.stdout.readline()
        t = threading.Thread(target=_read, daemon=True)
        t.start()
        t.join(max(0.0, t_end - time.monotonic()))
        if not line:
            raise TimeoutError(f"{self.name}: child never reported "
                               f"ready (rc={self._proc.poll()})")
        msg = json.loads(line)
        self.msgr.addr = tuple(msg["addr"])

    def _control(self, obj: dict) -> None:
        if self._stop.is_set():
            return
        try:
            with self._ctl_lock:
                self._proc.stdin.write(json.dumps(obj) + "\n")
                self._proc.stdin.flush()
        except (BrokenPipeError, OSError, ValueError):
            pass                      # child died; harness will see it

    def boot(self) -> None:
        """Tell the child to announce itself (MOSDBoot to the mons) —
        the revive_osd step the parent cannot send on the child's
        behalf."""
        self._control({"cmd": "boot"})

    # -- request/response control lines (r15 harness parity) ------------------

    def _request(self, obj: dict, timeout: float = 30.0) -> dict:
        """A control line that ANSWERS: ship {..., req: n} down stdin,
        read stdout lines until {event, req: n} comes back. Serialized
        under the control lock (the only other stdout traffic is the
        one-shot ready line wait_ready consumed)."""
        if self._stop.is_set():
            raise ConnectionError(f"{self.name}: child is dead")
        with self._ctl_lock:
            self._req_seq = getattr(self, "_req_seq", 0) + 1
            req = self._req_seq
            try:
                self._proc.stdin.write(
                    json.dumps({**obj, "req": req}) + "\n")
                self._proc.stdin.flush()
            except (BrokenPipeError, OSError, ValueError):
                raise ConnectionError(f"{self.name}: control pipe "
                                      f"closed")
            t_end = time.monotonic() + timeout
            while time.monotonic() < t_end:
                line = [None]

                def _read():
                    line[0] = self._proc.stdout.readline()
                t = threading.Thread(target=_read, daemon=True)
                t.start()
                t.join(max(0.0, t_end - time.monotonic()))
                if not line[0]:
                    break
                try:
                    msg = json.loads(line[0])
                except ValueError:
                    continue
                if msg.get("req") == req:
                    return msg
            raise TimeoutError(f"{self.name}: no reply to "
                               f"{obj.get('cmd')!r} control line")

    def push_rotating(self, service: str, rotating: list) -> None:
        """Key-rotation push (the in-process verifier.refresh parity
        path): rotated service secrets cross the child's stdin pipe —
        never argv — and refresh its in-RAM ServiceVerifier, so
        rotation composes with --osd-procs thrash cells."""
        got = self._request({"cmd": "rotate", "service": service,
                            "rotating": rotating})
        if not got.get("ok"):
            raise RuntimeError(f"{self.name}: rotation push failed: "
                               f"{got.get('error')}")

    def store_fsck(self, timeout: float = 60.0) -> dict:
        """Online store audit (the Thrasher store-fsck parity path):
        the child quiesces its store plane (store lock held) and runs
        the offline TinStore fsck over its own directory; MemStore
        children answer a trivial in-RAM audit. Returns the fsck
        report dict."""
        got = self._request({"cmd": "fsck"}, timeout=timeout)
        if not got.get("ok"):
            raise RuntimeError(f"{self.name}: store fsck failed: "
                               f"{got.get('error')}")
        return got["report"]

    def asok(self, cmd: str, timeout: float = 10.0):
        """Query the child's admin socket (shared admin_dir)."""
        from ..utils.admin_socket import admin_command
        return admin_command(self.c.asok_path(self.name), cmd,
                             timeout=timeout)

    def kill(self) -> None:
        """REAL SIGKILL — the process vanishes mid-whatever."""
        self._stop.set()
        try:
            self._proc.kill()
            self._proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def revive(self) -> "OSDProcHandle":
        """Fresh process, same store directory (the TinStore WAL
        remount path runs in the child at boot)."""
        fresh = OSDProcHandle.__new__(OSDProcHandle)
        fresh.c = self.c
        fresh.osd_id = self.osd_id
        fresh.name = self.name
        fresh.msgr = _HandleMsgr(fresh)
        fresh._ctl_lock = threading.Lock()
        fresh._spawn()
        fresh.wait_ready()
        return fresh


def _b64(b: bytes | None) -> str | None:
    return None if b is None else base64.b64encode(b).decode()


def _unb64(s: str | None) -> bytes | None:
    return None if s is None else base64.b64decode(s)


# -- child side ---------------------------------------------------------------

class _ChildKeyServer:
    """The one KeyServer method an OSD daemon consumes
    (export_rotating) served from the exported blob the parent
    shipped. Rotation pushes don't cross the pipe — documented
    in-process-only."""

    def __init__(self, rotating_osd):
        self._rot = {"osd": [tuple(x) for x in rotating_osd]}

    def export_rotating(self, service: str):
        return list(self._rot[service])


class _ChildCluster:
    """The StandaloneCluster surface OSDDaemon actually touches,
    rebuilt from the parent's config line. Static where the parent's
    is dynamic (mon_names doesn't track mon deaths — frames to a dead
    monitor queue in the lossless session, which is exactly what a
    real daemon does)."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self.secret = _unb64(cfg.get("secret"))
        self.compress = cfg.get("compress")
        self.profile = cfg["profile"]
        self.pg_num = cfg["pg_num"]
        self.pool_size = cfg["pool_size"]
        self.pool_min_size = cfg["pool_min_size"]
        self.is_erasure = cfg["is_erasure"]
        self.chunk_size = cfg["chunk_size"]
        self.op_timeout = cfg["op_timeout"]
        self.hb_interval = cfg["hb_interval"]
        self.hb_grace = cfg["hb_grace"]
        self.admin_dir = cfg["admin_dir"]
        self.op_shards = cfg.get("op_shards", 1)
        self.msgr_workers = cfg.get("msgr_workers", 1)
        self.msgr_uds = cfg.get("msgr_uds", True)
        self.verbose = cfg.get("verbose", False)
        self._mon_names = list(cfg["mon_names"])
        self._osd_ids = list(cfg["osd_ids"])
        self.key_server = None
        self.osd_secrets = {}
        if cfg.get("rotating_osd") is not None:
            self.key_server = _ChildKeyServer(cfg["rotating_osd"])
            self.osd_secrets = {
                cfg["osd_id"]: _unb64(cfg["osd_secret"])}

    def log(self, msg: str) -> None:
        from ..utils.log import dout
        dout("osd", 4, f"osd-proc: {msg}")
        if self.verbose:
            print(f"osd-proc: {msg}", file=sys.stderr, flush=True)

    def asok_path(self, name: str) -> str:
        return os.path.join(self.admin_dir, f"{name}.asok")

    def mon_names(self) -> list[str]:
        return list(self._mon_names)

    def osd_ids(self) -> list[int]:
        return list(self._osd_ids)

    def make_store(self, osd_id: int):
        if self.cfg["store"] == "tin":
            from .tinstore import TinStore
            return TinStore(os.path.join(self.cfg["store_dir"],
                                         f"osd.{osd_id}"),
                            verify_reads=False,
                            cache_bytes=64 << 10)
        from .memstore import MemStore
        return MemStore()


def child_main() -> int:
    line = sys.stdin.readline()
    if not line:
        return 1
    cfg = json.loads(line)
    # shared persistent jit cache BEFORE any jax import path runs:
    # sibling children and the parent reuse each other's compiles
    from ..utils.jax_cache import enable_persistent_compile_cache
    enable_persistent_compile_cache(cfg.get("jax_cache_dir"))
    from .standalone import MOSDBoot, OSDDaemon
    shim = _ChildCluster(cfg)
    daemon = OSDDaemon(cfg["osd_id"], shim)
    print(json.dumps({"event": "ready",
                      "addr": list(daemon.msgr.addr)}), flush=True)

    def _boot() -> None:
        for mon in shim.mon_names():
            try:
                daemon.msgr.send(mon, MOSDBoot(daemon.osd_id))
            except (KeyError, OSError, ConnectionError):
                pass
    def _answer(req, ok, **fields) -> None:
        print(json.dumps({"req": req, "ok": ok, **fields}),
              flush=True)

    def _fsck() -> dict:
        """Online audit: quiesce the store plane (store lock), then
        run the offline fsck over this child's own directory. A
        concurrent local write can at worst leave a torn WAL tail,
        which TinDB.fsck already classifies as recoverable — the
        caller judges `errors`/`bad_objects`, not torn_tail."""
        store = daemon.store
        path = getattr(store, "path", None)
        if path is None:
            # MemStore: nothing on disk — answer the in-RAM shape
            return {"format": "mem", "errors": [], "bad_objects": [],
                    "extent_errors": [],
                    "objects": sum(len(c) for c in
                                   store.collections.values())
                    if hasattr(store, "collections") else 0}
        from .tinstore import TinStore
        with daemon._store_lock:
            return TinStore.fsck(path)

    for raw in sys.stdin:        # EOF = parent gone: die with it
        try:
            ctl = json.loads(raw)
        except ValueError:
            continue
        cmd = ctl.get("cmd")
        req = ctl.get("req")
        try:
            if cmd == "add_peer":
                daemon.msgr.add_peer(ctl["peer"], tuple(ctl["addr"]))
            elif cmd == "boot":
                _boot()
            elif cmd == "set_blocked":
                daemon.msgr.set_blocked(set(ctl["peers"]))
            elif cmd == "seed_injection":
                daemon.msgr.seed_injection(ctl["seed"])
            elif cmd == "inject_socket_failures":
                daemon.msgr.set_inject_socket_failures(ctl["every"])
            elif cmd == "inject_delay":
                daemon.msgr.set_inject_delay(ctl["every"],
                                             ctl["max_ms"])
            elif cmd == "rotate":
                # key-rotation push (r15 parity): refresh the live
                # verifier AND the shim KeyServer, so the daemon's
                # own _start/revive paths see the rotated export too
                rot = [tuple(x) for x in ctl["rotating"]]
                if shim.key_server is not None:
                    shim.key_server._rot[ctl["service"]] = list(rot)
                if daemon.verifier is not None:
                    daemon.verifier.refresh(rot)
                if req is not None:
                    _answer(req, True)
            elif cmd == "fsck":
                _answer(req, True, report=_fsck())
            elif cmd == "shutdown":
                break
        except Exception as e:   # noqa: BLE001 — a bad control line
            shim.log(f"control {cmd!r} failed: {e!r}")   # is not fatal
            if req is not None:
                _answer(req, False, error=f"{type(e).__name__}: {e}")
    daemon.kill()
    return 0


if __name__ == "__main__":
    sys.exit(child_main())
