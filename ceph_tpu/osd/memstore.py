"""MemStore — the in-memory transactional object store.

Rebuild of the reference's test/fake backend (ref: src/os/memstore/
MemStore.{h,cc}; transactional API ref: src/os/ObjectStore.h —
ObjectStore::Transaction op-codes OP_WRITE/OP_TRUNCATE/OP_SETATTR/
OP_RM... applied atomically by queue_transaction). This is the store
the hermetic recovery/cluster tests run against, exactly as the
reference's store_test.cc runs one suite against MemStore and
BlueStore.

Objects live in collections (one per PG shard); each object holds byte
data (a numpy uint8 array), xattrs (small bytes: hinfo lives here), and
an omap dict. Transactions collect ops and apply all-or-nothing: any
op that fails validation aborts the whole batch before any mutation.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class _Object:
    data: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.uint8))
    xattrs: dict[str, bytes] = field(default_factory=dict)
    omap: dict[bytes, bytes] = field(default_factory=dict)


class Transaction:
    """Ordered op list; build with the helpers, apply via
    MemStore.queue_transaction."""

    def __init__(self):
        self.ops: list[tuple] = []

    def create_collection(self, cid: str):
        self.ops.append(("mkcoll", cid))
        return self

    def remove_collection(self, cid: str):
        self.ops.append(("rmcoll", cid))
        return self

    def touch(self, cid: str, oid: str):
        self.ops.append(("touch", cid, oid))
        return self

    def write(self, cid: str, oid: str, offset: int, data):
        if int(offset) < 0:
            raise ValueError(f"write offset {offset} < 0")
        # frombuffer reads memoryviews/bytes directly — bytes(data)
        # here would add a SECOND full copy per shard write on the
        # subop hot path (the .copy() below is the one that must
        # stay: a transaction owns its bytes, the aliasing contract)
        arr = (np.frombuffer(data, dtype=np.uint8).copy()
               if isinstance(data, (bytes, bytearray, memoryview))
               else np.asarray(data, np.uint8).copy())
        if arr.ndim != 1:
            raise ValueError(f"write data must be flat bytes, got {arr.shape}")
        self.ops.append(("write", cid, oid, int(offset), arr))
        return self

    def xor(self, cid: str, oid: str, offset: int, data):
        """XOR `data` into the object at `offset`, zero-extending past
        EOF (ref: the parity-delta apply of EC partial-stripe
        overwrites — MOSDECSubOpWrite carrying ECTransaction deltas).
        XOR into a zero-extended region degenerates to a plain write,
        so the op also serves delta writes past the old tail."""
        if int(offset) < 0:
            raise ValueError(f"xor offset {offset} < 0")
        arr = (np.frombuffer(data, dtype=np.uint8).copy()
               if isinstance(data, (bytes, bytearray, memoryview))
               else np.asarray(data, np.uint8).copy())
        if arr.ndim != 1:
            raise ValueError(f"xor data must be flat bytes, got {arr.shape}")
        self.ops.append(("xor", cid, oid, int(offset), arr))
        return self

    def truncate(self, cid: str, oid: str, size: int):
        if int(size) < 0:
            raise ValueError(f"truncate size {size} < 0")
        self.ops.append(("truncate", cid, oid, int(size)))
        return self

    def remove(self, cid: str, oid: str):
        self.ops.append(("remove", cid, oid))
        return self

    def setattr(self, cid: str, oid: str, key: str, value: bytes):
        self.ops.append(("setattr", cid, oid, key, bytes(value)))
        return self

    def rmattr(self, cid: str, oid: str, key: str):
        self.ops.append(("rmattr", cid, oid, key))
        return self

    def omap_set(self, cid: str, oid: str, kv: dict[bytes, bytes]):
        self.ops.append(("omap_set", cid, oid, dict(kv)))
        return self

    def omap_rmkeys(self, cid: str, oid: str, keys):
        """Remove specific omap keys (ref: src/os/ObjectStore.h
        OP_OMAP_RMKEYS) — without this, KV entries could only grow or
        die with the object."""
        self.ops.append(("omap_rmkeys", cid, oid,
                         [bytes(k) for k in keys]))
        return self

    def omap_clear(self, cid: str, oid: str):
        """Drop every omap key (ref: OP_OMAP_CLEAR)."""
        self.ops.append(("omap_clear", cid, oid))
        return self


class MemStore:
    """All state in RAM; crash-consistency is trivially atomic because
    transactions apply under a copy-validate-commit discipline."""

    #: no on-disk footprint (the lifecycle contract shared w/ TinStore)
    path: str | None = None

    def __init__(self, capacity_bytes: int = 0):
        self.collections: dict[str, dict[str, _Object]] = {}
        self.committed_txns = 0
        #: capacity ceiling in bytes; 0 = unbounded (no statfs ratio,
        #: no ENOSPC). Live-shrinkable via set_capacity — the r21
        #: disk_full injection path.
        self.capacity_bytes = int(capacity_bytes)
        #: deterministic ENOSPC injection hook: fn(point) called at
        #: "txn.apply" before any mutation; raising OSError there
        #: aborts the whole batch (nothing applied — trivially atomic)
        self._fault = None

    # -- lifecycle (shared store contract; see tinstore.TinStore) -----------
    # RAM-only semantics: "process death keeps bytes by fiat", so
    # crash/remount are no-ops and the store is never down.

    @property
    def is_down(self) -> bool:
        return False

    def crash(self) -> None:
        pass

    def remount(self) -> None:
        pass

    # -- capacity (r21 capacity plane; contract shared w/ TinStore) ---------

    def set_capacity(self, nbytes: int) -> None:
        """Live capacity change (shrinkable below current usage — the
        ratio then reads > 1.0 and every new mutation ENOSPCs, which
        is exactly what the disk_full fault stream wants)."""
        self.capacity_bytes = int(nbytes)

    def set_fault(self, fn) -> None:
        self._fault = fn

    def used_bytes(self) -> int:
        total = 0
        for coll in self.collections.values():
            for o in coll.values():
                total += len(o.data)
                total += sum(len(v) for v in o.xattrs.values())
                total += sum(len(k) + len(v)
                             for k, v in o.omap.items())
        return total

    def statfs(self) -> dict:
        """Bytes total/used/avail (the ObjectStore::statfs contract).
        total == 0 means unbounded: the mon ladder never computes a
        ratio for such a store."""
        used = self.used_bytes()
        total = int(self.capacity_bytes)
        return {"total": total, "used": used,
                "avail": max(0, total - used) if total else 0}

    def _txn_grow_bytes(self, txn: Transaction) -> int:
        """Conservative upper bound of bytes this batch can ADD —
        growth is what ENOSPC gates; frees inside the same batch are
        deliberately not credited (a real allocator can't reuse them
        until commit either)."""
        grow = 0
        for op in txn.ops:
            kind = op[0]
            if kind in ("write", "xor"):
                grow += len(op[4])
            elif kind == "truncate":
                grow += op[3]
            elif kind == "setattr":
                grow += len(op[4])
            elif kind == "omap_set":
                grow += sum(len(k) + len(v) for k, v in op[3].items())
        return grow

    # -- transaction apply --------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        self._validate(txn)
        if self._fault is not None:
            # injection point BEFORE any mutation: an injected ENOSPC
            # aborts with nothing applied (atomic by construction)
            self._fault("txn.apply")
        cap = self.capacity_bytes
        grow = self._txn_grow_bytes(txn) if cap else 0
        # zero-growth batches (deletes, truncate-down, omap rm) pass
        # even when usage already exceeds a shrunk capacity: freeing
        # space is how a full store recovers
        if cap and grow and self.used_bytes() + grow > cap:
            import errno
            raise OSError(errno.ENOSPC,
                          f"memstore over capacity ({cap} bytes)")
        for op in txn.ops:
            self._apply(op)
        self.committed_txns += 1

    def _validate(self, txn: Transaction) -> None:
        # simulate the ObjectStore contract: ops referencing missing
        # collections are caller bugs -> abort before mutating anything
        cols = set(self.collections)
        for op in txn.ops:
            kind = op[0]
            if kind == "mkcoll":
                cols.add(op[1])
            elif kind == "rmcoll":
                if op[1] not in cols:
                    raise KeyError(f"rmcoll: no collection {op[1]!r}")
                cols.discard(op[1])
            else:
                if op[1] not in cols:
                    raise KeyError(f"{kind}: no collection {op[1]!r}")

    def _obj(self, cid: str, oid: str, create: bool = False) -> _Object:
        coll = self.collections[cid]
        if oid not in coll:
            if not create:
                raise KeyError(f"no object {cid}/{oid}")
            coll[oid] = _Object()
        return coll[oid]

    def _apply(self, op: tuple) -> None:
        kind = op[0]
        if kind == "mkcoll":
            self.collections.setdefault(op[1], {})
        elif kind == "rmcoll":
            self.collections.pop(op[1])
        elif kind == "touch":
            self._obj(op[1], op[2], create=True)
        elif kind == "write":
            _, cid, oid, off, data = op
            o = self._obj(cid, oid, create=True)
            end = off + len(data)
            if end > len(o.data):
                grown = np.zeros(end, dtype=np.uint8)
                grown[:len(o.data)] = o.data
                o.data = grown
            o.data[off:end] = data
        elif kind == "xor":
            _, cid, oid, off, data = op
            o = self._obj(cid, oid, create=True)
            end = off + len(data)
            if end > len(o.data):
                grown = np.zeros(end, dtype=np.uint8)
                grown[:len(o.data)] = o.data
                o.data = grown
            o.data[off:end] ^= data
        elif kind == "truncate":
            _, cid, oid, size = op
            o = self._obj(cid, oid, create=True)
            if size == len(o.data):
                pass    # the write-then-truncate-to-length pattern on
                #         every shard subop: already exact, and the
                #         .copy() below would re-copy the whole object
            elif size <= len(o.data):
                o.data = o.data[:size].copy()
            else:
                grown = np.zeros(size, dtype=np.uint8)
                grown[:len(o.data)] = o.data
                o.data = grown
        elif kind == "remove":
            self.collections[op[1]].pop(op[2], None)
        elif kind == "setattr":
            self._obj(op[1], op[2], create=True).xattrs[op[3]] = op[4]
        elif kind == "rmattr":
            # tolerant like remove: a missing object is a no-op, so the
            # all-or-nothing apply contract can't break mid-transaction
            o = self.collections[op[1]].get(op[2])
            if o is not None:
                o.xattrs.pop(op[3], None)
        elif kind == "omap_set":
            self._obj(op[1], op[2], create=True).omap.update(op[3])
        elif kind == "omap_rmkeys":
            # tolerant like rmattr: a missing object/key is a no-op so
            # the all-or-nothing apply contract can't break mid-batch
            o = self.collections[op[1]].get(op[2])
            if o is not None:
                for k in op[3]:
                    o.omap.pop(k, None)
        elif kind == "omap_clear":
            o = self.collections[op[1]].get(op[2])
            if o is not None:
                o.omap.clear()
        else:
            raise ValueError(f"unknown op {kind!r}")

    # -- reads --------------------------------------------------------------

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> np.ndarray:
        o = self._obj(cid, oid)
        if length is None:
            return o.data[offset:].copy()
        return o.data[offset:offset + length].copy()

    def read_batch(self, cid: str, oids: list[str], length: int,
                   out: np.ndarray | None = None) -> np.ndarray:
        """(len(oids), length) stack of equal-length objects in one
        copy each (the recovery staging path reads B objects per shard;
        per-object read() would copy twice — once into the temporary,
        once into the caller's stack). Pass `out` (any (len(oids),
        length) uint8 view) to fill the caller's buffer directly."""
        if out is None:
            out = np.empty((len(oids), length), np.uint8)
        for i, oid in enumerate(oids):
            d = self._obj(cid, oid).data
            if len(d) != length:
                # a stale/partially-written shard must fail LOUDLY
                # here — zero-filling would hand the decoder garbage
                # that writeback then stamps with matching CRCs
                raise ValueError(
                    f"read_batch: {oid!r} is {len(d)} bytes, "
                    f"expected {length}")
            out[i] = d
        return out

    def stat(self, cid: str, oid: str) -> int:
        return len(self._obj(cid, oid).data)

    def getattr(self, cid: str, oid: str, key: str) -> bytes:
        return self._obj(cid, oid).xattrs[key]

    def exists(self, cid: str, oid: str) -> bool:
        return cid in self.collections and oid in self.collections[cid]

    def list_objects(self, cid: str, start_after: str | None = None,
                     limit: int | None = None) -> list[str]:
        """Flat-dict listing: sorts the WHOLE collection per call —
        O(n log n) in collection size no matter how small the page
        (the linear baseline TinStore's KV-plane paginated iterator
        replaces; store_bench's `list` workload measures the gap)."""
        names = sorted(self.collections.get(cid, {}))
        if start_after is not None:
            import bisect
            names = names[bisect.bisect_right(names, start_after):]
        return names if limit is None else names[:limit]

    def list_collections(self) -> list[str]:
        return sorted(self.collections)

    def omap_iter(self, cid: str, oid: str,
                  start_after: bytes | None = None,
                  limit: int | None = None) -> list[tuple[bytes, bytes]]:
        """Ordered omap page — flat-dict cost: sorts the whole omap
        per call (same linear baseline as list_objects)."""
        om = self._obj(cid, oid).omap
        keys = sorted(om)
        if start_after is not None:
            import bisect
            keys = keys[bisect.bisect_right(keys, bytes(start_after)):]
        if limit is not None:
            keys = keys[:limit]
        return [(k, om[k]) for k in keys]
