"""Repair policy plane — WHEN and IN WHAT ORDER to repair, not how.

The stack below this module already repairs FAST (r10 fused recovery
batches, r14 minimal-helper plans, r16 delta writes); what it lacked
was judgement. The Facebook warehouse study (arxiv 1309.0186) measured
that recovery traffic — not client IO — is what saturates erasure-coded
clusters, and that the large majority of "failures" are transient: a
daemon back in 90 seconds does not deserve a multi-gigabyte rebuild.
This module is the policy layer between failure detection and the r14
planner, three mechanisms:

* **DownClock + lazy repair.** A per-OSD state machine
  (up -> suspect -> down_deferred -> down_confirmed) driven by the
  evidence the daemon already has: heartbeat/complaint suspicion and
  the committed map's down marks. While a peer is `down_deferred`
  (map-down for less than `osd_repair_delay`), shard rebuilds for it
  are PARKED — the reconcile pass plans nothing and moves nothing. A
  revive inside the window cancels the parked work with only a
  cursor/version re-check (the PG-log missing-set walk; zero bytes
  when no write landed in the window). The delay loses to three
  overrides: a stripe at m-1 surviving redundancy (one more failure =
  data loss) repairs immediately, an outstanding-stripe budget
  (`osd_repair_deferred_max_stripes`) bounds the exposure a patient
  policy can accumulate, and an OUT mark (the operator or
  mon_osd_down_out_interval said permanent) confirms instantly.

* **Risk-ordered burst recovery.** On multi-failure events the rebuild
  queue orders by stripe risk — fewest surviving redundancy shards
  first, ties broken by the r14 plan's helper cost (cheapest exposure
  reduction first), then PG id for determinism — so cumulative
  stripe-time at m-1 shrinks even when total repair time is unchanged
  (the queue is a schedule; risk order is shortest-exposure-first).

* **Per-failure-domain repair budgets.** Repair grants draw from token
  buckets keyed by the CRUSH failure domain of the helper set
  (scheduler.DomainBudgets), so one rack's burst rebuild cannot
  saturate another rack's uplinks; enforcement rides the existing
  mClock `background_recovery` grant path — a grant whose domains are
  out of tokens re-queues instead of executing.

Everything here is clock-agnostic (`now` is a parameter) so the scale
sim replays a day of churn in virtual time through the SAME policy
object the live daemon runs, and config resolves AT CALL TIME through
the daemon's layered Config — a committed `config set osd_repair_*`
retunes a running policy with no restart.
"""

from __future__ import annotations

from typing import Callable, Iterable

__all__ = ["DownClock", "RepairPolicy", "risk_key", "order_plans",
           "exposure_units"]


class DownClock:
    """One OSD's failure-classification state machine.

    States and the evidence that moves them:

      up             healthy (map up, no suspicion)
      suspect        heartbeat/complaint suspicion, map still up —
                     reads/writes already route around it; repair
                     policy does nothing yet (the mon may disagree)
      down_deferred  the committed map marked it down; rebuilds are
                     parked until the repair delay elapses (or an
                     override fires)
      down_confirmed the delay elapsed / a threshold or m-1 override
                     fired / the OSD was marked out: rebuild for real

    A revive (map up again) from either down state returns to `up` and
    counts a FLAP when the down dwell was shorter than the delay — the
    signal the lazy-repair delay exists to absorb."""

    UP = "up"
    SUSPECT = "suspect"
    DOWN_DEFERRED = "down_deferred"
    DOWN_CONFIRMED = "down_confirmed"

    __slots__ = ("state", "down_since", "confirmed_reason", "flaps",
                 "transitions")

    def __init__(self):
        self.state = self.UP
        self.down_since: float | None = None
        self.confirmed_reason: str | None = None
        self.flaps = 0
        self.transitions = 0

    def _to(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self.transitions += 1

    def mark_suspect(self) -> None:
        if self.state == self.UP:
            self._to(self.SUSPECT)

    def clear_suspect(self) -> None:
        if self.state == self.SUSPECT:
            self._to(self.UP)

    def mark_down(self, now: float) -> None:
        if self.state in (self.DOWN_DEFERRED, self.DOWN_CONFIRMED):
            return
        self.down_since = now
        self.confirmed_reason = None
        self._to(self.DOWN_DEFERRED)

    def mark_up(self, now: float, delay: float) -> bool:
        """Map says up again. Returns True when this revive cancels a
        deferral window that was still open (the lazy-repair win)."""
        was_deferred = self.state == self.DOWN_DEFERRED
        if self.state in (self.DOWN_DEFERRED, self.DOWN_CONFIRMED):
            if self.down_since is not None \
                    and now - self.down_since < max(delay, 0.0):
                self.flaps += 1
        self.down_since = None
        self.confirmed_reason = None
        self._to(self.UP)
        return was_deferred

    def confirm(self, reason: str) -> None:
        """Deferral lost: delay elapsed, stripe budget blown, m-1
        override, or an OUT mark. One-way until the next revive."""
        if self.state == self.DOWN_DEFERRED:
            self.confirmed_reason = reason
            self._to(self.DOWN_CONFIRMED)

    def maybe_confirm_elapsed(self, delay: float, now: float) -> bool:
        if self.state == self.DOWN_DEFERRED \
                and self.down_since is not None \
                and now - self.down_since >= max(delay, 0.0):
            self.confirm("delay_elapsed")
        return self.state == self.DOWN_CONFIRMED

    def dump(self) -> dict:
        return {"state": self.state, "down_since": self.down_since,
                "confirmed_reason": self.confirmed_reason,
                "flaps": self.flaps, "transitions": self.transitions}


#: every counter the policy keeps — the daemon mirrors these into its
#: declared PerfCounters under the same names (r9 discipline: declared
#: once, asserted by the observability smoke)
POLICY_COUNTERS = (
    "repair_deferred_stripes",       # stripes parked behind the delay
    "repair_deferred_cancelled",     # parked PGs cancelled by a revive
    "repair_deferred_confirmed",     # parked PGs that went to rebuild
    "repair_cancel_noop",            # revive re-checks that moved 0 B
    "repair_catchup_objects",        # objects the cursor re-check DID
    #                                  have to replay (writes landed
    #                                  inside the window)
    "repair_urgent_overrides",       # m-1 stripes that beat the delay
    "repair_urgent_parked",          # MUST STAY 0: an at-risk stripe
    #                                  was parked (invariant checker)
    "repair_risk_inversions",        # MUST STAY 0 under risk order: a
    #                                  healthier stripe was queued
    #                                  ahead of an exposed one
    "repair_domain_throttles",       # grants deferred by a domain
    #                                  token bucket
    "repair_time_at_m1_ms",          # cumulative stripe-time at m-1
    # r21 capacity plane
    "repair_backfillfull_parked",    # rounds parked: a replacement
    #                                  target sat at/over backfillfull
    "repair_enospc_parked",          # rounds parked: writeback hit
    #                                  ENOSPC mid-rebuild (cursors
    #                                  intact, retried next reconcile)
    # r22 network plane
    "slow_link_suspects",            # peers marked DownClock-suspect
    #                                  on measured slow-link evidence
    #                                  (hb RTT ewma over the slow-ping
    #                                  line; one tick per flip)
)


class RepairPolicy:
    """The daemon-side policy state: DownClocks for every peer, the
    parked-rebuild table, revive re-check queue, and the time-at-m-1
    accounting. Owned per OSDDaemon (policy is local to the primary
    that would plan the repair, exactly like the reconcile pass);
    in-RAM like the rest of the observability plane — a restarted
    primary starts conservative (unknown down peers confirm
    immediately; see `observe_map`)."""

    def __init__(self, config=None, perf=None,
                 now_fn: Callable[[], float] | None = None):
        # config: a utils.config.Config (or any mapping); resolved at
        # CALL time so committed central-config changes apply live
        self._config = config
        self._perf = perf
        self._now = now_fn
        self.clocks: dict[int, DownClock] = {}
        # ps -> {"dead": set, "since": t, "lost": n, "stripes": n}
        self.parked: dict[int, dict] = {}
        # ps -> revived osd ids whose shards need the cursor re-check
        self.rechecks: dict[int, set[int]] = {}
        # ps -> wall stamp the PG was first seen at m-1 redundancy
        self._exposed_since: dict[int, float] = {}
        self.counters: dict[str, int] = {k: 0 for k in POLICY_COUNTERS}
        self._last_up: dict[int, bool] = {}

    # -- plumbing ------------------------------------------------------------

    def _cfg(self, key: str, default):
        if self._config is None:
            return default
        try:
            return self._config[key]
        except KeyError:
            return default

    def _count(self, key: str, n: int = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + n
        if self._perf is not None:
            try:
                self._perf.inc(key, n)
            except KeyError:
                pass    # harness perf without the declared schema

    def clock(self, osd: int) -> DownClock:
        if osd not in self.clocks:
            self.clocks[osd] = DownClock()
        return self.clocks[osd]

    @property
    def delay(self) -> float:
        return float(self._cfg("osd_repair_delay", 0.0))

    @property
    def max_deferred_stripes(self) -> int:
        return int(self._cfg("osd_repair_deferred_max_stripes", 512))

    @property
    def queue_order(self) -> str:
        return str(self._cfg("osd_repair_queue_order", "risk"))

    # -- evidence ------------------------------------------------------------

    def observe_map(self, osd_up: Iterable[bool], out_osds:
                    Iterable[int] = (), now: float | None = None,
                    suspect: Iterable[int] = ()) -> list[int]:
        """Fold one committed map's liveness into the clocks. Returns
        the osds that REVIVED (down -> up) so the caller can cancel
        parked work and queue cursor re-checks for them.

        First observation semantics: an OSD already down in the very
        first map this policy sees has an UNKNOWN down stamp (the
        previous primary's RAM died with it) — it confirms immediately.
        Deferring an unknowable window would gamble data safety on a
        guess, so a restarted primary is eager, not patient."""
        now = self._now() if now is None and self._now else (now or 0.0)
        first = not self._last_up
        revived: list[int] = []
        up_list = list(osd_up)
        out = set(out_osds)
        susp = set(suspect)
        for osd, up in enumerate(up_list):
            ck = self.clock(osd)
            prev = self._last_up.get(osd)
            if up:
                if prev is False or ck.state in (DownClock.DOWN_DEFERRED,
                                                 DownClock.DOWN_CONFIRMED):
                    ck.mark_up(now, self.delay)
                    revived.append(osd)
                if osd in susp:
                    ck.mark_suspect()
                else:
                    ck.clear_suspect()
            else:
                ck.mark_down(now)
                if first:
                    ck.confirm("unknown_down_at_boot")
                if osd in out:
                    ck.confirm("marked_out")
            self._last_up[osd] = bool(up)
        if revived:
            for ps, ent in list(self.parked.items()):
                hit = ent["dead"] & set(revived)
                if hit:
                    self.rechecks.setdefault(ps, set()).update(hit)
                    ent["dead"] -= hit
                    if not ent["dead"]:
                        self.parked.pop(ps, None)
                        self._count("repair_deferred_cancelled")
        return revived

    def note_suspect(self, osd: int) -> None:
        self.clock(osd).mark_suspect()

    def note_slow_link(self, osd: int) -> None:
        """r22: measured slow-link evidence (heartbeat RTT ewma over
        the slow-ping line) — same DownClock suspect mark as
        heartbeat silence, but counted separately so operators can
        tell a sick WIRE from a silent peer."""
        self.clock(osd).mark_suspect()
        self._count("slow_link_suspects")

    # -- decisions -----------------------------------------------------------

    def should_defer(self, ps: int, dead_osds: Iterable[int],
                     n_lost: int, redundancy: int, n_stripes: int,
                     now: float | None = None) -> bool:
        """One PG's park-or-plan decision for `n_lost` lost slots whose
        old holders are `dead_osds`, on a code tolerating `redundancy`
        losses. True = park (lazy). The overrides, in order:

        * delay <= 0 (policy off) or any dead holder unknown/confirmed
          -> plan now;
        * m-1 override: surviving redundancy <= 1 -> plan NOW, count
          the override, and confirm the holders (a second stripe of
          the same OSD must not re-enter deferral);
        * stripe budget: parked stripes past
          osd_repair_deferred_max_stripes -> plan now.
        """
        now = self._now() if now is None and self._now else (now or 0.0)
        delay = self.delay
        dead = {int(o) for o in dead_osds}
        if n_lost <= 0 or not dead:
            return False
        urgent = (redundancy - n_lost) <= 1
        if urgent:
            if any(self.clock(o).state == DownClock.DOWN_DEFERRED
                   for o in dead):
                self._count("repair_urgent_overrides")
                for o in dead:
                    self.clock(o).confirm("m1_override")
            self._unpark(ps)
            return False
        if delay <= 0:
            return False
        for o in dead:
            ck = self.clock(o)
            if ck.state != DownClock.DOWN_DEFERRED:
                return False
            if ck.maybe_confirm_elapsed(delay, now):
                self._count("repair_deferred_confirmed")
                self._unpark(ps)
                return False
        outstanding = sum(e["stripes"] for e in self.parked.values()
                          if e is not self.parked.get(ps))
        if outstanding + n_stripes > self.max_deferred_stripes:
            for o in dead:
                self.clock(o).confirm("stripe_budget")
            self._count("repair_deferred_confirmed")
            self._unpark(ps)
            return False
        if ps not in self.parked:
            self._count("repair_deferred_stripes", n_stripes)
        self.parked[ps] = {"dead": dead, "since":
                           self.parked.get(ps, {}).get("since", now),
                           "lost": n_lost, "stripes": n_stripes}
        return True

    def _unpark(self, ps: int) -> None:
        self.parked.pop(ps, None)

    def note_planned(self, ps: int) -> None:
        """A rebuild for this PG is actually being planned — drop any
        parked record (the plan subsumes it)."""
        self._unpark(ps)

    def take_recheck(self, ps: int) -> set[int]:
        """The revived osds whose shards this PG must cursor-check
        (consumed — the re-check runs once per revive)."""
        return self.rechecks.pop(ps, set())

    def note_recheck(self, moved_objects: int) -> None:
        if moved_objects:
            self._count("repair_catchup_objects", moved_objects)
        else:
            self._count("repair_cancel_noop")

    # -- exposure accounting ---------------------------------------------------

    def note_exposure(self, ps: int, at_m1: bool,
                      now: float | None = None) -> None:
        """Track cumulative stripe-time at m-1 redundancy (the metric
        risk ordering exists to shrink). Transitions accumulate into
        repair_time_at_m1_ms; steady state costs a dict probe."""
        now = self._now() if now is None and self._now else (now or 0.0)
        if at_m1:
            self._exposed_since.setdefault(ps, now)
        else:
            t0 = self._exposed_since.pop(ps, None)
            if t0 is not None:
                self._count("repair_time_at_m1_ms",
                            max(0, int((now - t0) * 1000)))

    def exposed_pgs(self) -> int:
        return len(self._exposed_since)

    # -- introspection ---------------------------------------------------------

    def dump(self) -> dict:
        return {
            "counters": dict(self.counters),
            "parked": {str(ps): {"dead": sorted(e["dead"]),
                                 "since": e["since"],
                                 "lost": e["lost"],
                                 "stripes": e["stripes"]}
                       for ps, e in sorted(self.parked.items())},
            "exposed_pgs": self.exposed_pgs(),
            "clocks": {str(o): ck.dump()
                       for o, ck in sorted(self.clocks.items())
                       if ck.state != DownClock.UP or ck.flaps},
            "config": {"osd_repair_delay": self.delay,
                       "osd_repair_deferred_max_stripes":
                           self.max_deferred_stripes,
                       "osd_repair_queue_order": self.queue_order},
        }


# -- queue ordering ------------------------------------------------------------

def risk_key(redundancy_left: int, helper_cost: float, ps: int
             ) -> tuple:
    """The rebuild queue's sort key: most exposed first (fewest
    surviving redundancy shards), cheapest helper plan second (an
    exposed stripe that repairs in half the bytes halves its residual
    exposure window), PG id last for determinism."""
    return (redundancy_left, helper_cost, ps)


def plan_helper_cost(plan) -> float:
    """Tie-break cost of one r14 plan: helper rows on the wire scaled
    by the sub-chunk fraction (what the planner minimized)."""
    rp = getattr(plan, "repair", None)
    frac = rp.wire_fraction if rp is not None else 1.0
    return len(getattr(plan, "helper", ())) * frac


def order_plans(entries, redundancy_of, mode: str = "risk",
                counter: Callable[[str, int], None] | None = None):
    """Order a reconcile pass's [(ps, plan, dead)] rebuild entries.

    mode="risk" sorts by risk_key; mode="pgid" keeps PG-id order (the
    pre-r17 behavior, kept selectable so the exposure comparison stays
    measurable) but COUNTS the inversions it ships — every position
    where a healthier stripe precedes a more exposed one increments
    repair_risk_inversions, the invariant signal the thrasher asserts
    stays 0 under risk order."""
    def key(ent):
        ps, plan, _dead = ent
        left = redundancy_of(ps, plan)
        return risk_key(left, plan_helper_cost(plan), ps)

    ranked = sorted(entries, key=key)
    out = ranked if mode == "risk" else sorted(entries,
                                               key=lambda e: e[0])
    if counter is not None:
        inversions = 0
        lefts = [redundancy_of(ps, plan) for ps, plan, _d in out]
        for i in range(len(lefts)):
            for j in range(i + 1, len(lefts)):
                if lefts[i] > lefts[j]:
                    inversions += 1
        if inversions:
            counter("repair_risk_inversions", inversions)
    return out


def exposure_units(queue: Iterable[tuple[int, float, bool]]) -> float:
    """Cumulative exposure of a rebuild schedule: for every stripe at
    m-1 redundancy, the work units processed until IT completes (its
    position in the schedule, cost-weighted). The unit is
    bytes-processed x stripes-exposed — a pure count, so risk-vs-pgid
    comparisons are deterministic on any box.

    queue: ordered (pg, rebuild_cost, at_m1) entries."""
    done = 0.0
    exposure = 0.0
    for _pg, cost, at_m1 in queue:
        done += float(cost)
        if at_m1:
            exposure += done
    return exposure
