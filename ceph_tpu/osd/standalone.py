"""Standalone cluster — the control plane on REAL wire traffic.

Where SimCluster models the cluster in-process under virtual time,
this module runs it the way the reference's qa/standalone tier does
(ref: qa/standalone/ceph-helpers.sh run_osd/run_mon/wait_for_clean):
N OSD daemons + 3 monitors + clients as independent endpoints on
localhost, every interaction a typed, CRC/AES-GCM-protected frame on
the Messenger — nothing reaches around the wire:

* client I/O:      MOSDOp / MOSDOpReply        (ref: MOSDOp.h)
* shard writes:    MStoreOp / MStoreReply       (the MOSDECSubOpWrite
  role: the PG primary fans per-shard store transactions out to the
  OSDs that own them; reads pull helper shards back the same way)
* liveness:        MOSDPing / MOSDPingReply     (ref: MOSDPing.h)
* failure reports: MOSDFailure -> monitor       (ref: MOSDFailure.h)
* map commits:     MMonCollect / MMonLast / MMonBegin / MMonAcceptPn /
  MMonCommit / MMonNack — multi-phase Paxos with rank-stamped proposal
  numbers (ref: src/mon/Paxos.cc collect/last/begin/accept/commit)
* map fan-out:     MOSDMap epoch + full encoded OSDMap (MOSDMap.h)
* boot:            MOSDBoot                     (ref: MOSDBoot.h)

Key design points, and what they re-validate from the in-process sim:

* The PG backends are the SAME ECBackend/ReplicatedBackend classes —
  unchanged — but their ShardSet hands out RemoteStore proxies, so
  every queue_transaction/read/getattr/exists a backend performs
  becomes a blocking RPC to the OSD that owns the bytes (its own
  shard short-circuits to the local store). The "exactly-once,
  lossless" messenger guarantees are thereby exercised under real
  workload ordering, not just test_msgr's synthetic schedules.
* PG metadata travels WITH the data (the reference's transactions
  carry pg_log entries to every shard): after each write the primary
  persists {object_sizes, versions, pg_log, cursors} as an omap blob
  on every live shard, so a surviving acting member can take over as
  primary from its local copy after the old primary dies.
* Failure detection is emergent: OSDs ping each other in real time,
  report unanswered peers to the monitor leader, the leader commits
  down+out through its quorum and broadcasts the new epoch; primaries
  then recover the lost slot onto the CRUSH replacement — every step
  as frames.
* Op ordering: client ops execute under ONE daemon lock — a TOTAL
  order per primary, a strict superset of the reference's guarantee
  (PrimaryLogPG::execute_ctx orders per object within a PG; ops on
  different objects/PGs may interleave there). Every ordering the
  reference promises holds here by construction; what this tier does
  NOT model is the reference's cross-PG op CONCURRENCY (OSDShard
  queues) — per-PG parallel dispatch is a scaling concern of the
  CPU daemon, deliberately traded away in a tier whose batched data
  plane does its parallelism inside device launches (SURVEY §2.7 P2).

Scope: this tier proves the wire transport under daemon death AND
the monitor control plane on the same wire — rank election over ping
liveness, multi-phase Paxos map commits whose safety holds under
network partitions and dual-leader windows (pn arbitration, not
election correctness — see MonDaemon), leader death, revived-leader
resync (collect doubles as store sync), and injected partitions
(Messenger.set_blocked / StandaloneCluster.partition) all run as
frames. The in-process mon/monitor.py layer remains the synchronous
model used by the sim tier. Secure mode composes: pass secret= to
run the whole cluster over AES-GCM sessions.
"""

from __future__ import annotations

import struct
import threading
import time

import numpy as np

from ..msgr.messenger import Message, Messenger, register_message
from ..utils.encoding import Decoder, Encoder
from ..utils.flight_recorder import current as _trace_current
from ..utils.flight_recorder import declare_span_names
from .ecbackend import ECBackend, ShardSet, shard_cid
from .memstore import MemStore, Transaction
from .osdmap import (FULL_BACKFILLFULL, FULL_FULL, FULL_NEARFULL,
                     FULL_STATE_NAMES, Incremental, OSDMap, PGPool)
from .pgbackend import ReplicatedBackend
from .pglog import PGLog, divergent_names, share_history
from .tinstore import _decode_txn, _encode_txn, _encode_txn_iov

PG_META_KEY = b"pg_meta"
#: delta-meta omap key (same omap object as PG_META_KEY): entries
#: appended since the last full base blob — see OSDDaemon._meta_extra
PG_META_DELTA_KEY = b"pg_meta_delta"
#: full-base persist cadence: a delta may cover at most this many
#: entries before the next write re-ships the full blob
_META_DELTA_MAX = 32

# every span name this module's hops may record into a flight ring
# (the r9 no-undeclared-names invariant, extended to the trace plane;
# ecbackend's span() sites declare themselves through the same call —
# the observability smoke asserts no ring carries an undeclared name)
declare_span_names(
    "client.op", "client.hedge",
    "osd.queue", "osd.op", "osd.subop", "store.apply",
    "osd.recovery_round",
    "osd.repair_policy", "osd.repair_throttle",
    "msgr.seal",
    "ecbackend.write.encode", "ecbackend.read.decode",
    "ecbackend.recover.stage", "ecbackend.recover.launch",
    "ecbackend.recover.fetch", "ecbackend.recover.writeback",
    "ecbackend.recover.batch",
)


# -- typed frames (0x30 block) ----------------------------------------------

class _Blob(Message):
    """Shared shape: (req_id, ok, kind, payload-bytes). `blob` may be
    one buffer or a segment list (Encoder.segments output): either way
    it is appended BY REFERENCE, so an op body carrying object data
    crosses the encode + framing path without a copy. Decoded messages
    always carry contiguous bytes.

    `trace` (r15) is an OPTIONAL, VERSION-GATED tail field carrying a
    distributed-tracing context (ref: MOSDOp::otel_trace riding the
    message): a frame without one encodes the v1 section BIT-IDENTICAL
    to the pre-r15 wire (pinned by tests/test_msgr_frames.py), a frame
    with one encodes v2/compat-1 — a legacy decoder's finish() skips
    the field, a new decoder reads it only when the writer declared
    v >= 2 AND bytes remain in the section (legacy-sender interop)."""

    def __init__(self, req_id: int, ok: bool = True, kind: str = "",
                 blob=b"", err: str = "", trace=None):
        self.req_id, self.ok = req_id, ok
        self.kind, self.blob, self.err = kind, blob, err
        self.trace = trace           # TraceContext | None

    def encode_payload(self, e: Encoder) -> None:
        if self.trace is None:
            (e.start(1, 1).u64(self.req_id).boolean(self.ok)
             .string(self.kind).blob_ref(self.blob).string(self.err)
             .finish())
            return
        (e.start(2, 1).u64(self.req_id).boolean(self.ok)
         .string(self.kind).blob_ref(self.blob).string(self.err)
         .blob(self.trace.encode()).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "_Blob":
        v = d.start(2)
        m = cls(d.u64(), d.boolean(), d.string(), d.blob(), d.string())
        if v >= 2 and d.remaining_in_section() >= 4:
            raw = d.blob()
            if raw:
                from ..utils.flight_recorder import TraceContext
                m.trace = TraceContext.decode(raw)
        d.finish()
        return m


@register_message
class MStoreOp(_Blob):
    type_id = 0x30


@register_message
class MStoreReply(_Blob):
    type_id = 0x31


@register_message
class MOSDOp(_Blob):
    type_id = 0x32


@register_message
class MOSDOpReply(_Blob):
    type_id = 0x33


@register_message
class MOSDPing(Message):
    type_id = 0x34

    def __init__(self, stamp: float):
        self.stamp = stamp

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).f64(self.stamp).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDPing":
        d.start(1)
        m = cls(d.f64())
        d.finish()
        return m


@register_message
class MOSDPingReply(MOSDPing):
    type_id = 0x35


@register_message
class MOSDFailure(Message):
    """A failure report — or its CANCELLATION when `alive` (ref:
    MOSDFailure FLAG_ALIVE: the reporter heard the peer again and
    retracts; without retraction a transient stall's stale report
    could later combine with one more false report into a spurious
    down-mark)."""

    type_id = 0x36

    def __init__(self, failed: int, alive: bool = False):
        self.failed = failed
        self.alive = alive

    def encode_payload(self, e: Encoder) -> None:
        e.start(2, 1).i32(self.failed).boolean(self.alive).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDFailure":
        v = d.start(2)
        m = cls(d.i32(), d.boolean() if v >= 2 else False)
        d.finish()
        return m


@register_message
class MOSDBoot(MOSDFailure):
    type_id = 0x37          # payload: the booting osd id


@register_message
class MOSDAlive(Message):
    """up_thru request (ref: MOSDAlive -> OSDMonitor::prepare_alive):
    `osd` asks the monitors to record that it is up through map epoch
    `want` — the activation proof its fresh primary intervals need
    before they may serve I/O (PeeringState WaitUpThru)."""

    type_id = 0x48

    def __init__(self, osd: int, want: int):
        self.osd, self.want = osd, want

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).i32(self.osd).u64(self.want).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOSDAlive":
        d.start(1)
        m = cls(d.i32(), d.u64())
        d.finish()
        return m


@register_message
class MMonPropose(Message):
    type_id = 0x38

    def __init__(self, epoch: int, map_bytes: bytes):
        self.epoch, self.map_bytes = epoch, map_bytes

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).u32(self.epoch).blob(self.map_bytes).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonPropose":
        d.start(1)
        m = cls(d.u32(), d.blob())
        d.finish()
        return m


@register_message
class MMonAccept(Message):
    type_id = 0x39

    def __init__(self, epoch: int):
        self.epoch = epoch

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).u32(self.epoch).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonAccept":
        d.start(1)
        m = cls(d.u32())
        d.finish()
        return m


@register_message
class MOSDMapMsg(MMonPropose):
    type_id = 0x3A          # same shape: epoch + encoded map


@register_message
class MOSDIncMapMsg(MMonPropose):
    """Incremental map fan-out (ref: MOSDMap carrying incremental_maps
    instead of maps): epoch + encoded OSDMap.Incremental whose
    base_epoch rides inside. Subscribers that can't chain it (gap,
    fresh boot) ask for a full map with MOSDMapRequest."""
    type_id = 0x4C


@register_message
class MOSDMapRequest(MMonAccept):
    """Subscriber -> monitor full-map request (the on-request half of
    the full-map-every-Nth-epoch cadence): payload is the requester's
    current epoch; any monitor answers with its committed full map."""
    type_id = 0x4D


@register_message
class MMonSyncReq(MMonAccept):
    type_id = 0x3B          # payload: requester's current epoch


# Multi-phase Paxos frames (ref: src/mon/Paxos.cc collect/last/begin/
# accept/commit; OP_COLLECT..OP_COMMIT in Paxos.h). Proposal numbers
# are rank-stamped (pn = n*256 + rank) so they are globally unique and
# totally ordered across proposers.

@register_message
class MMonCollect(Message):
    type_id = 0x3C

    def __init__(self, pn: int):
        self.pn = pn

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).u64(self.pn).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonCollect":
        d.start(1)
        m = cls(d.u64())
        d.finish()
        return m


@register_message
class MMonLast(Message):
    """Peon's collect reply: its promise for `pn`, any accepted-but-
    uncommitted value, and its committed map (epoch 0 = none) so a
    stale or fresh leader catches up from the quorum it gathers."""

    type_id = 0x3D

    def __init__(self, pn: int, accepted_pn: int, accepted_epoch: int,
                 accepted_blob: bytes, committed_epoch: int,
                 committed_blob: bytes):
        self.pn = pn
        self.accepted_pn = accepted_pn
        self.accepted_epoch = accepted_epoch
        self.accepted_blob = accepted_blob
        self.committed_epoch = committed_epoch
        self.committed_blob = committed_blob

    def encode_payload(self, e: Encoder) -> None:
        (e.start(1, 1).u64(self.pn).u64(self.accepted_pn)
         .u32(self.accepted_epoch).blob(self.accepted_blob)
         .u32(self.committed_epoch).blob(self.committed_blob).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonLast":
        d.start(1)
        m = cls(d.u64(), d.u64(), d.u32(), d.blob(), d.u32(), d.blob())
        d.finish()
        return m


@register_message
class MMonBegin(Message):
    type_id = 0x3E

    def __init__(self, pn: int, epoch: int, map_bytes: bytes):
        self.pn, self.epoch, self.map_bytes = pn, epoch, map_bytes

    def encode_payload(self, e: Encoder) -> None:
        (e.start(1, 1).u64(self.pn).u32(self.epoch)
         .blob(self.map_bytes).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonBegin":
        d.start(1)
        m = cls(d.u64(), d.u32(), d.blob())
        d.finish()
        return m


@register_message
class MMonAcceptPn(Message):
    type_id = 0x3F

    def __init__(self, pn: int, epoch: int):
        self.pn, self.epoch = pn, epoch

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).u64(self.pn).u32(self.epoch).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonAcceptPn":
        d.start(1)
        m = cls(d.u64(), d.u32())
        d.finish()
        return m


@register_message
class MMonCommit(MMonPropose):
    type_id = 0x40          # same shape: epoch + encoded map


@register_message
class MMonNack(Message):
    """Refusal carrying the REFUSED pn, the refuser's promise and its
    committed state: the rejected proposer adopts the committed map
    and, if the nack is for its CURRENT round (stale replayed nacks
    must not abort a later healthy round), abandons and re-collects
    at a higher pn (the Paxos 'learn you lost' path)."""

    type_id = 0x41

    def __init__(self, nacked: int, promised: int, committed_epoch: int,
                 committed_blob: bytes):
        self.nacked = nacked
        self.promised = promised
        self.committed_epoch = committed_epoch
        self.committed_blob = committed_blob

    def encode_payload(self, e: Encoder) -> None:
        (e.start(1, 1).u64(self.nacked).u64(self.promised)
         .u32(self.committed_epoch).blob(self.committed_blob).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonNack":
        d.start(1)
        m = cls(d.u64(), d.u64(), d.u32(), d.blob())
        d.finish()
        return m


@register_message
class MPoolOp(Message):
    """Client pool mutation — mksnap/rmsnap by NAME (ref: MPoolOp.h,
    OSDMonitor::prepare_pool_op). Broadcast to every monitor like
    MOSDBoot; name-idempotence makes the queue-everywhere pattern
    commit exactly one snap. The client observes the result through
    its map subscription (pg_pool_t.snaps rides the OSDMap)."""

    type_id = 0x42

    def __init__(self, kind: str, snap_name: str):
        self.kind, self.snap_name = kind, snap_name

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).string(self.kind).string(self.snap_name).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPoolOp":
        d.start(1)
        m = cls(d.string(), d.string())
        d.finish()
        return m


@register_message
class MPoolQuotaOp(Message):
    """`ceph osd pool set-quota` over the wire (r21, ref: OSDMonitor
    prepare_command POOL_SET quota_max_bytes/objects): quotas ride
    the committed map like every pool attribute, so the capacity
    ladder's quota evaluation reads from Paxos state, never from a
    side channel. Broadcast to every monitor; value-idempotent."""

    type_id = 0x4E

    def __init__(self, pool_id: int, max_bytes: int, max_objects: int):
        self.pool_id = pool_id
        self.max_bytes, self.max_objects = max_bytes, max_objects

    def encode_payload(self, e: Encoder) -> None:
        (e.start(1, 1).u32(self.pool_id).u64(self.max_bytes)
         .u64(self.max_objects).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MPoolQuotaOp":
        d.start(1)
        m = cls(d.u32(), d.u64(), d.u64())
        d.finish()
        return m


@register_message
class MConfigOp(Message):
    """Centralized config mutation — `ceph config set/rm` (ref:
    MMonCommand routed to ConfigMonitor::prepare_command). Broadcast
    to every monitor like MPoolOp; value-idempotence (OSDMap.config_set
    bumps nothing when unchanged) makes queue-everywhere commit exactly
    one change. Daemons observe it through their map subscription and
    apply it at their config's "mon" layer."""

    type_id = 0x43

    def __init__(self, kind: str, key: str, value: str = ""):
        self.kind, self.key, self.value = kind, key, value

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).string(self.kind).string(self.key) \
            .string(self.value).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MConfigOp":
        d.start(1)
        m = cls(d.string(), d.string(), d.string())
        d.finish()
        return m


def _daemon_authorize(verifier, req: dict, peer: str, req_id: int,
                      authed: dict, export_fn) -> "MAuthReply":
    """Shared daemon-side MAuthOp('authorize') handling (OSDs and
    monitors): run the challenge round, auto-refresh rotating secrets
    once when the presented secret_id is newer than this daemon's
    window (the fetch-from-mon-on-newer-sid behavior), bind the
    session on success."""
    import json as _json

    from ..auth import AuthError, NeedChallenge

    def _try() -> "MAuthReply":
        got = verifier.verify(req, peer=peer)
        authed[peer] = {"entity": got["entity"], "caps": got["caps"]}
        return MAuthReply(req_id, True, "authorize",
                          _json.dumps({"reply_mac":
                                       got["reply_mac"].hex()})
                          .encode())
    try:
        try:
            return _try()
        except NeedChallenge:
            raise
        except AuthError as e:
            if "rotated out" in str(e):
                verifier.refresh(export_fn())
                return _try()
            raise
    except NeedChallenge as nc:
        return MAuthReply(req_id, False, "authorize",
                          err=f"EAGAIN:challenge:{nc.challenge}")
    except Exception as e:   # noqa: BLE001 — reply, don't die
        return MAuthReply(req_id, False, "authorize",
                          err=f"{type(e).__name__}:{e}")


@register_message
class MMonJoin(Message):
    """Monitor membership change request (ref: MMonJoin.h; `ceph mon
    add/remove`): rank + direction. Queued like any map mutation;
    the leader commits it through Paxos, so quorum math changes
    atomically with the committed map."""

    type_id = 0x46

    def __init__(self, rank: int, join: bool):
        self.rank, self.join = rank, join

    def encode_payload(self, e: Encoder) -> None:
        e.start(1, 1).i32(self.rank).boolean(self.join).finish()

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MMonJoin":
        d.start(1)
        m = cls(d.i32(), d.boolean())
        d.finish()
        return m


@register_message
class MOsdAdmin(Message):
    """`ceph osd out/in/reweight` over the wire (ref: OSDMonitor
    prepare_command OSD_OUT/OSD_IN/OSD_REWEIGHT): admin-plane
    broadcast, quorum-committed like pool/config ops. weight is
    16.16 fixed-point over 0x10000 (the reference's convention)."""

    type_id = 0x47

    def __init__(self, kind: str, osd: int, weight: float = 1.0):
        if not 0.0 <= weight <= 1.0:
            # the reference clamps reweight to [0,1]; refusing at
            # construction beats a struct.error deep in the codec
            raise ValueError(f"osd weight {weight} outside [0, 1]")
        self.kind, self.osd, self.weight = kind, osd, weight

    def encode_payload(self, e: Encoder) -> None:
        (e.start(1, 1).string(self.kind).i32(self.osd)
         .u32(int(self.weight * 0x10000)).finish())

    @classmethod
    def decode_payload(cls, d: Decoder) -> "MOsdAdmin":
        d.start(1)
        m = cls(d.string(), d.i32(), d.u32() / 0x10000)
        d.finish()
        return m


@register_message
class MAuthOp(_Blob):
    """cephx traffic (ref: MAuth/MAuthReply): kind selects the auth
    method (hello / authenticate / tickets against a monitor;
    authorize against an OSD); blob is the JSON request with byte
    fields hex-armored."""
    type_id = 0x44


@register_message
class MAuthReply(_Blob):
    type_id = 0x45


@register_message
class MMgrReport(_Blob):
    """Daemon -> monitor stats report (ref: MMgrReport.h): kind is
    "full" or "delta", blob is the JSON report the MgrReportAggregator
    ingests (perf dump/delta + op stats + primary-claimed PG states).
    Broadcast to every monitor fire-and-forget; each folds its own
    aggregate, so any monitor can answer `ceph status`."""

    type_id = 0x49


@register_message
class MMonCmd(_Blob):
    """Read-only monitor command (the MMonCommand slice observability
    needs): kind names the command (status / health / health detail /
    prometheus / perf dump / report dump); the reply blob is JSON."""

    type_id = 0x4A


@register_message
class MMonCmdReply(_Blob):
    type_id = 0x4B


# -- request/reply plumbing --------------------------------------------------

class _PendingCall:
    """One in-flight rpc: event + slot accounting. wait() returns the
    reply or raises ConnectionError on timeout — exactly call()'s
    contract, split so callers can have MANY of these on the wire."""

    __slots__ = ("_rpc", "rid", "peer", "nbytes", "_ev", "_replies",
                 "_released", "_waiters")

    def __init__(self, rpc: "_Rpc", rid: int, peer: str, nbytes: int):
        self._rpc = rpc
        self.rid, self.peer, self.nbytes = rid, peer, nbytes
        self._ev = threading.Event()
        self._replies: list = []
        self._released = False
        self._waiters: list[threading.Event] = []

    def wait(self, timeout: float = 10.0):
        try:
            if not self._ev.wait(timeout):
                self._rpc.perf.inc("op_timeout")
                raise ConnectionError(f"rpc to {self.peer} timed out")
            rep = self._replies[0]
            if isinstance(rep, BaseException):
                raise rep
            return rep
        finally:
            self._rpc._retire(self)

    # -- hedged-read surface: wait-any without retiring -----------------------

    def ready(self, timeout: float | None = 0.0) -> bool:
        """Reply (or transport error) arrived? Unlike wait(), does NOT
        retire the handle — the hedging client polls many handles and
        claims only the winner."""
        return self._ev.wait(timeout)

    def take(self):
        """Claim a ready() handle: the reply, or raises its transport
        error. Retires exactly like wait() — call once."""
        try:
            rep = self._replies[0]
            if isinstance(rep, BaseException):
                raise rep
            return rep
        finally:
            self._rpc._retire(self)

    def cancel(self) -> None:
        """Abandon the op: frees the window slot NOW and drops any
        late reply on the floor (_on_reply pops the table entry, so a
        straggler reply no longer matches). The hedging client's
        loser-cancellation path; retiring twice is a no-op, so a
        cancel racing the reply is safe either way."""
        self._rpc._retire(self)

    def add_waiter(self, ev: threading.Event) -> None:
        """Signal `ev` (too) on completion — the wait-any primitive the
        hedge loop blocks on instead of polling."""
        self._waiters.append(ev)
        if self._ev.is_set():   # completion raced the registration
            ev.set()

    def _notify(self) -> None:
        self._ev.set()
        for ev in self._waiters:
            ev.set()

    def fail(self, err: BaseException) -> None:
        self._replies.append(err)
        self._notify()


class _Rpc:
    """Request/reply over the messenger: correlation ids + per-request
    events; reply handlers route by req_id, so completions match OUT
    OF ORDER. submit() opens a windowed in-flight op (the Objecter's
    seq-tagged pipeline role); call() is submit()+wait() — one op per
    round trip, the pre-window behavior.

    The window (ops cap + byte budget) bounds how much a caller may
    pipeline: submit() BLOCKS while the window is full (backpressure,
    the objecter_inflight_op_bytes role) and a completion — in any
    order — frees its slot. window=0 disables the cap (daemon-internal
    rpc must never backpressure dispatch threads against each other)."""

    def __init__(self, msgr: Messenger, reply_type: int,
                 window: int = 0, window_bytes: int = 0):
        from ..utils.perf_counters import PerfCountersBuilder
        self.msgr = msgr
        self._lock = threading.Lock()
        self._next = 1
        self._pending: dict[int, _PendingCall] = {}
        self.window = int(window)
        self.window_bytes = int(window_bytes)
        self._win = threading.Condition(self._lock)
        self._inflight = 0
        self._inflight_bytes = 0
        # op-window observability (the objecter_ops / objecter_bytes
        # counters the reference's Objecter logger carries): occupancy
        # gauges, submit/reply counters, and the backpressure stall
        # time a full window cost submitters
        self.perf = (PerfCountersBuilder("rpc")
                     .add_u64_counter("op_send", "ops submitted")
                     .add_u64_counter("op_reply", "replies matched")
                     .add_u64_counter("op_timeout", "waits timed out")
                     .add_u64_counter("op_send_bytes",
                                      "payload bytes submitted")
                     .add_u64_counter("window_stalls",
                                      "submits that blocked on a "
                                      "full window")
                     .add_u64("inflight_ops", "ops on the wire now")
                     .add_u64("inflight_bytes",
                              "payload bytes on the wire now")
                     .add_time_avg("window_stall_time",
                                   "backpressure wait per stalled "
                                   "submit")
                     .create_perf_counters())
        msgr.register_handler(reply_type, self._on_reply)

    def _on_reply(self, peer: str, msg) -> None:
        with self._lock:
            # pop, not get: an abandoned handle (caller gave up before
            # the late reply landed) must not leak its table entry
            ent = self._pending.pop(msg.req_id, None)
            if ent is not None:
                # the slot frees the moment the ack arrives (not when
                # the waiter gets scheduled): the window refills at
                # wire speed even with a slow consumer
                self._release_locked(ent)
        if ent is not None:
            self.perf.inc("op_reply")
            ent._replies.append(msg)
            ent._notify()

    def _release_locked(self, ent: _PendingCall) -> None:
        if ent._released:
            return
        ent._released = True
        self._inflight -= 1
        self._inflight_bytes -= ent.nbytes
        self.perf.set("inflight_ops", self._inflight)
        self.perf.set("inflight_bytes", self._inflight_bytes)
        self._win.notify_all()

    def _retire(self, ent: _PendingCall) -> None:
        with self._lock:
            self._pending.pop(ent.rid, None)
            self._release_locked(ent)

    def submit(self, peer: str, make_msg,
               nbytes: int = 0) -> _PendingCall:
        """make_msg(req_id) -> Message. Transmits and returns the
        pending handle immediately (blocking first while the window is
        full). The reply — or a transport error — is delivered through
        handle.wait()."""
        with self._lock:
            if self.window:
                t0 = None
                while (self._inflight >= self.window
                       or (self.window_bytes and self._inflight
                           and self._inflight_bytes + nbytes
                           > self.window_bytes)):
                    if t0 is None:
                        t0 = time.perf_counter()
                    self._win.wait()
                if t0 is not None:
                    # backpressure accounting: how long a full window
                    # held this submitter (the stall the r8 bench
                    # could only guess at)
                    self.perf.inc("window_stalls")
                    self.perf.tinc("window_stall_time",
                                   time.perf_counter() - t0)
            rid = self._next
            self._next += 1
            ent = _PendingCall(self, rid, peer, nbytes)
            self._pending[rid] = ent
            self._inflight += 1
            self._inflight_bytes += nbytes
            self.perf.inc_many((("op_send", 1),
                                ("op_send_bytes", nbytes)))
            self.perf.set("inflight_ops", self._inflight)
            self.perf.set("inflight_bytes", self._inflight_bytes)
        try:
            self.msgr.send(peer, make_msg(rid))
        except KeyError:
            # unknown endpoint (peer not wired yet / torn down):
            # a TRANSPORT failure, never to be confused with an
            # application-level KeyError reply ("no such omap
            # key") — peering quorum counts only peers that
            # actually ANSWERED
            self._retire(ent)
            ent.fail(ConnectionError(
                f"rpc to {peer}: endpoint unknown"))
        except (OSError, ConnectionError) as e:
            # the lossless messenger queues + replays on reconnect, so
            # most transport errors never surface here; a hard refusal
            # (partition injection) does — fail the handle, not the
            # batch
            self._retire(ent)
            ent.fail(ConnectionError(f"rpc to {peer}: {e}"))
        return ent

    def call(self, peer: str, make_msg, timeout: float = 10.0):
        """make_msg(req_id) -> Message. Returns the reply or raises
        ConnectionError on timeout (the caller treats the peer as
        suspect — the OSD op timeout role)."""
        return self.submit(peer, make_msg).wait(timeout)


class _AsyncStoreOp:
    """In-flight MStoreOp with the same error surface as
    RemoteStore._call: result() maps the reply like the sync path,
    including the one cephx re-authorize retry on a cold session."""

    def __init__(self, rs: "RemoteStore", kind: str, body: bytes):
        self._rs, self._kind, self._body = rs, kind, body
        self._pending = rs._submit(kind, body)

    def result(self) -> bytes:
        rs = self._rs
        rep = self._pending.wait(rs._timeout)
        if not rep.ok and rep.err == "EPERM:unauthenticated" \
                and rs._authorize is not None:
            # first store op to this peer since (re)boot: run the
            # osd->osd cephx round, then retry once
            rs._authorize(rs._peer)
            rep = rs._submit(self._kind, self._body).wait(rs._timeout)
        if rep.ok:
            return rep.blob
        if rep.err.startswith("KeyError"):
            raise KeyError(rep.err[9:] or rep.err)
        raise ConnectionError(f"store op {self._kind} on {rs._peer}: "
                              f"{rep.err}")


class _ReadvOp:
    """In-flight readv: result() -> (data bytes, attrs list | None),
    with _AsyncStoreOp's error surface (incl. the one cephx
    re-authorize retry)."""

    def __init__(self, rs: "RemoteStore", body: bytes, want_attrs: bool):
        self._op = _AsyncStoreOp(rs, "readv", body)
        self._want_attrs = want_attrs

    def result(self) -> tuple[bytes, list[bytes] | None]:
        d = Decoder(self._op.result())
        data = d.blob()
        attrs = d.list(Decoder.blob)
        return data, (attrs if self._want_attrs else None)


class _ReadvRangesOp:
    """In-flight ranged readv (the sub-chunk pull frame): result() ->
    (data bytes, range CRC list | None, source-flagged bad row
    indices), same error surface as _AsyncStoreOp."""

    def __init__(self, rs: "RemoteStore", body: bytes, want_crcs: bool):
        self._op = _AsyncStoreOp(rs, "readv_ranges", body)
        self._want_crcs = want_crcs

    def result(self) -> tuple[bytes, list[int] | None, list[int]]:
        d = Decoder(self._op.result())
        data = d.blob()
        crcs = d.list(Decoder.u32)
        bad = d.list(Decoder.u32)
        return data, (crcs if self._want_crcs else None), bad


class _RmwFetchOp:
    """In-flight combined RMW prepare fetch: result() -> per item
    (attr_present, attr bytes, [range bytes]), same error surface as
    _AsyncStoreOp (incl. the one cephx re-authorize retry)."""

    def __init__(self, rs: "RemoteStore", body: bytes):
        self._op = _AsyncStoreOp(rs, "rmw_fetch", body)

    def result(self) -> list[tuple[bool, bytes, list[bytes]]]:
        d = Decoder(self._op.result())
        return d.list(lambda dd: (dd.boolean(), dd.blob(),
                                  dd.list(Decoder.blob)))


class RemoteStore:
    """ObjectStore proxy: the MOSDECSubOpWrite/Read role. Every method
    is one MStoreOp frame to the OSD owning the physical store."""

    path = None

    def __init__(self, rpc: _Rpc, peer: str, timeout: float = 10.0,
                 authorize=None, on_latency=None):
        self._rpc = rpc
        self._peer = peer
        self._timeout = timeout
        self._authorize = authorize   # cephx: establish session, retry
        # on_latency(peer, seconds): per-reply round-trip report — the
        # owning daemon folds it into its peer-latency EWMA, which the
        # repair planner consumes as per-helper read costs
        self._on_latency = on_latency

    def _submit(self, kind: str, body):
        # trace propagation (r15/r18): whatever context is active on
        # THIS thread (a client op mid-fan-out, a recovery round
        # mid-pull) rides the sub-op frame. Sampled contexts make the
        # helper's spans land under the same trace eagerly; since r18
        # an UNSAMPLED context travels too (17 bytes) so the serving
        # hop can remember its window in the sub-op retro ring — what
        # lets a later slow-op retro assembly cover replicas instead
        # of reporting their time as wire. Absent context costs one
        # contextvar read and zero wire bytes.
        ctx = _trace_current()
        return self._rpc.submit(
            self._peer,
            lambda rid: MStoreOp(rid, True, kind, body, trace=ctx))

    def _call(self, kind: str, body: bytes = b"") -> bytes:
        for attempt in range(2):
            t0 = time.perf_counter()
            rep = self._submit(kind, body).wait(self._timeout)
            if self._on_latency is not None:
                self._on_latency(self._peer,
                                 time.perf_counter() - t0)
            if rep.ok:
                return rep.blob
            if (rep.err == "EPERM:unauthenticated"
                    and self._authorize is not None and attempt == 0):
                # first store op to this peer since (re)boot: run the
                # osd->osd cephx round, then retry once
                self._authorize(self._peer)
                continue
            break
        if rep.err.startswith("KeyError"):
            raise KeyError(rep.err[9:] or rep.err)
        raise ConnectionError(f"store op {kind} on {self._peer}: "
                              f"{rep.err}")

    @staticmethod
    def _co(cid: str, oid: str = "", extra=None) -> bytes:
        e = Encoder()
        e.string(cid).string(oid)
        if extra is not None:
            extra(e)
        return e.bytes()

    def queue_transaction(self, txn: Transaction) -> None:
        self._call("txn", _encode_txn_iov(txn))

    def queue_transaction_async(self, txn: Transaction):
        """Pipelined txn: transmit now, ack later. Returns a handle
        whose .result() blocks until the peer committed (same
        durability point as the sync path — callers wait ALL handles
        before acking upward) and raises exactly what queue_transaction
        would. The PG fan-out uses this so n shard sub-ops cost one
        overlapped round trip instead of n sequential ones (the
        reference's parallel MOSDECSubOpWrite dispatch)."""
        return _AsyncStoreOp(self, "txn", _encode_txn_iov(txn))

    def read(self, cid: str, oid: str, offset: int = 0,
             length: int | None = None) -> np.ndarray:
        body = self._co(cid, oid, lambda e: e.i64(offset)
                        .i64(-1 if length is None else length))
        return np.frombuffer(self._call("read", body), np.uint8).copy()

    def readv_submit(self, cid: str, oids: list[str], length: int,
                     attr_key: str | None = None) -> "_ReadvOp":
        """Pipelined multi-object fetch: ONE readv frame carries every
        row (+ optional per-row attr) for `oids`; transmit now, collect
        later. The recovery runner submits one of these per (PG,
        helper shard) before awaiting any — pulls from different
        source OSDs overlap (the windowed PULL)."""
        body = self._co(cid, "", lambda e: e.string(attr_key or "")
                        .i64(length).list(list(oids), Encoder.string))
        return _ReadvOp(self, body, attr_key is not None)

    def readv_ranges_submit(self, cid: str, oids: list[str],
                            length: int, ranges,
                            attr_key: str | None = None
                            ) -> "_ReadvRangesOp":
        """Pipelined sub-chunk fetch (the repair-locality planner's
        wire frame): ONE frame names the (offset, length) ranges every
        row ships — the helper moves only the planned bytes. With
        `attr_key` the SOURCE verifies each full shard against its
        stored hinfo (rot detection stays intact without the receiver
        ever seeing the whole row) and ships per-row crc32c over the
        planned bytes for the receiver's fold verify."""
        body = self._co(cid, "", lambda e: e.string(attr_key or "")
                        .i64(length)
                        .list([(int(o), int(ln)) for o, ln in ranges],
                              lambda en, r: en.i64(r[0]).i64(r[1]))
                        .list(list(oids), Encoder.string))
        return _ReadvRangesOp(self, body, attr_key is not None)

    def rmw_fetch_submit(self, cid: str, attr_key: str,
                         items) -> "_RmwFetchOp":
        """Pipelined combined RMW prepare fetch (r17): ONE frame per
        participant shard carries, for every delta job in the wave,
        the hinfo attr probe AND the touched pre-image sub-ranges —
        collapsing the 1+m tiny sequential getattrs plus per-span
        pre-reads that used to precede every partial-stripe fan-out
        into one overlapped round trip per shard.
        items: [(name, [(off, len), ...])] — ranges may be empty
        (attr-only probe: parity shards and growth participants)."""
        def enc(e: Encoder) -> None:
            e.string(attr_key)
            e.list(list(items), lambda en, it: (
                en.string(it[0])
                .list([(int(o), int(ln)) for o, ln in it[1]],
                      lambda e2, r: e2.i64(r[0]).i64(r[1]))))
        return _RmwFetchOp(self, self._co(cid, "", enc))

    def stat(self, cid: str, oid: str) -> int:
        return Decoder(self._call("stat", self._co(cid, oid))).i64()

    def getattr(self, cid: str, oid: str, key: str) -> bytes:
        return self._call(
            "getattr", self._co(cid, oid, lambda e: e.string(key)))

    def exists(self, cid: str, oid: str) -> bool:
        return bool(self._call("exists", self._co(cid, oid))[0])

    def exists_submit(self, cid: str, oid: str) -> "_AsyncStoreOp":
        """Pipelined existence probe: transmit now, collect later —
        the stripe-journal replay scan probes every shard in ONE
        overlapped round trip instead of n sequential ones."""
        return _AsyncStoreOp(self, "exists", self._co(cid, oid))

    def list_objects(self, cid: str) -> list[str]:
        d = Decoder(self._call("ls", self._co(cid)))
        return d.list(Decoder.string)

    def omap_get(self, cid: str, oid: str, key: bytes) -> bytes:
        return self._call(
            "omap_get", self._co(cid, oid, lambda e: e.blob(key)))

    def omap_iter(self, cid: str, oid: str,
                  start_after: bytes | None = None,
                  limit: int | None = None) -> list[tuple[bytes, bytes]]:
        """Ordered omap page — the stripe-journal replay scan's frame
        (one page per call, same contract as the local stores)."""
        body = self._co(cid, oid, lambda e: e
                        .boolean(start_after is not None)
                        .blob(start_after or b"")
                        .i64(-1 if limit is None else int(limit)))
        d = Decoder(self._call("omap_iter", body))
        return d.list(lambda dd: (dd.blob(), dd.blob()))


# -- daemons -----------------------------------------------------------------

class _PgClsView:
    """SimCluster-shaped facade over ONE PG at its primary so object
    classes (objclass.py ClsHandle) run unchanged at the wire tier
    (ref: PrimaryLogPG::do_osd_ops OP_CALL — the method executes at
    the object's primary; its writes ride the normal fan-out path,
    COW and PG log included)."""

    def __init__(self, daemon: "OSDDaemon", ps: int, be):
        self._d, self._ps, self._be = daemon, ps, be
        self.pgs = {ps: be}

    def locate(self, name: str) -> int:
        return self._ps

    def read(self, name: str):
        return self._be.read_object(
            name, dead_osds=set(self._d.suspect))

    def write(self, objects: dict) -> None:
        d = self._d
        d._snap_guard(self._ps, self._be, objects)
        self._be.write_objects(
            {k: bytes(np.asarray(v, np.uint8).tobytes())
             if not isinstance(v, (bytes, bytearray)) else bytes(v)
             for k, v in objects.items()},
            dead_osds=set(d.suspect))
        # the cls branch of _client_op persists once after cls_call

    def remove(self, names) -> None:
        names = [names] if isinstance(names, str) else list(names)
        self._d._delete_objects(self._ps, self._be, names)

    @property
    def obj_kv(self) -> dict:
        return self._d.obj_kv.setdefault(self._ps, {})


class _RecoveryRound:
    """One mClock-governed pass of the cross-PG recovery runner: every
    grant executes ONE fused batch under the daemon lock then yields
    (re-enqueues itself, after osd_recovery_sleep), so client ops
    interleave between batches instead of waiting out the whole
    rebuild. The runner's push window is sized by the recovery
    reservation knobs: osd_recovery_max_active in-flight pushes,
    osd_recovery_max_active * osd_recovery_max_chunk bytes."""

    def __init__(self, daemon: "OSDDaemon", entries):
        from .ecbackend import RecoveryRunner
        self.d = daemon
        self.entries = entries            # [(ps, plan, dead osd ids)]
        self.plans = {ps: plan for ps, plan, _ in entries}
        self.dead: set[int] = set()
        for _ps, _plan, dead in entries:
            self.dead |= dead
        cfg = daemon.config
        max_active = int(cfg["osd_recovery_max_active"])
        # r17: the integrity mode resolves through config (auto keeps
        # the pre-r17 native-detect; 'device' forces the fused
        # decode+fold on-device; 'host' asserts the native crc path
        # when the lib is present — the storm bench verifies rebuilt
        # bytes against the full-decode oracle in both modes)
        from .ecbackend import _host_crc_available
        integ = str(cfg["osd_recovery_integrity"]).lower()
        host_crc = (False if integ == "device"
                    else True if integ == "host"
                    and _host_crc_available() else None)
        self.runner = RecoveryRunner(
            [plan for _ps, plan, _dead in entries],
            batch=int(cfg["osd_recovery_batch"]),
            perf=daemon.ec_perf,
            push_window_ops=max_active,
            push_window_bytes=max_active
            * int(cfg["osd_recovery_max_chunk"]),
            host_crc=host_crc)
        self.failed = False
        # r15: recovery rounds get their own sampled trace context
        # (rate-gated) — every fused batch then records its stage/
        # launch/fetch/writeback spans, and the readv/readv_ranges
        # helper pulls carry the context to their sources, whose
        # osd.subop spans land under the same trace.
        from ..utils.flight_recorder import (TraceContext, coin,
                                             new_trace_id)
        self.trace_ctx = None
        try:
            rate = float(cfg["osd_trace_recovery_sample_rate"])
        except (KeyError, ValueError):
            rate = 0.0
        if coin(rate):
            self.trace_ctx = TraceContext(new_trace_id(), 0,
                                          sampled=True)

    def lost_of(self, ps: int) -> list[int]:
        return self.plans[ps].lost

    def shard(self):
        """All of a round's grants ride ONE op shard (the lowest
        member PG's) so a client op waits behind at most one batch of
        its own shard; other shards never see the round."""
        return self.d._shard_of(min(self.plans))

    def next_cost(self) -> float:
        """One grant's work in client-op cost units (bytes-scaled, the
        osd_mclock_cost_per_byte role)."""
        return max(1.0, self.runner.next_cost()
                   / float(self.d.config["osd_recovery_max_chunk"]))

    def __call__(self) -> None:
        # each grant executes one fused batch under the round's trace
        # context (if sampled): the stage/launch/fetch/writeback spans
        # and the helper pulls' osd.subop spans all land in one trace
        from ..utils.flight_recorder import activate, trace_span
        with activate(self.trace_ctx,
                      self.d.flight if self.trace_ctx is not None
                      else None):
            with trace_span("osd.recovery_round",
                            pgs=sorted(self.plans)):
                self._grant()

    def _domain_throttle(self) -> float:
        """r17 per-failure-domain repair budget: the next batch's
        helper bytes draw from token buckets keyed by each helper's
        CRUSH rack. Returns 0.0 (granted) or the seconds to defer —
        the grant re-queues instead of executing, so enforcement rides
        the existing mClock background_recovery path and one rack's
        burst cannot saturate another rack's uplinks. Budgets resolve
        through config at every grant (live retune)."""
        d = self.d
        mbps = float(d.config["osd_repair_domain_budget_mbps"])
        if mbps <= 0 or d.osdmap is None:
            return 0.0
        helpers = self.runner.next_helper_osds()
        if not helpers:
            return 0.0
        nbytes = float(self.runner.next_cost())
        crush = d.osdmap.crush
        share = nbytes / len(helpers)
        domain_bytes: dict = {}
        for o in helpers:
            dom = crush.domain_of(int(o))
            domain_bytes[dom] = domain_bytes.get(dom, 0.0) + share
        wait = d.domain_budgets.request(
            domain_bytes, mbps * 1e6,
            float(d.config["osd_repair_domain_burst_mb"]) * 1e6,
            time.monotonic())
        if wait > 0.0:
            d.repair_policy._count("repair_domain_throttles")
            from ..utils.flight_recorder import trace_span
            with trace_span("osd.repair_throttle",
                            wait_ms=int(wait * 1000),
                            domains=len(domain_bytes)):
                pass
        return wait

    def _grant(self) -> None:
        d = self.d
        wait = self._domain_throttle()
        if wait > 0.0:
            # out of domain tokens: yield the shard worker and come
            # back when the bucket has refilled (bounded nap so a
            # live budget raise is picked up promptly)
            t = threading.Timer(min(wait, 0.5), self._requeue)
            t.daemon = True
            t.start()
            return
        # the daemon lock plus EVERY member PG's lock (ascending —
        # the one global order): a fused batch may touch any plan's
        # PG, and client ops on other shards hold only pg locks now
        locks = [d._pg_lock(ps) for ps in sorted(self.plans)]
        try:
            with d._lock:
                for lk in locks:
                    lk.acquire()
                try:
                    if self.runner.step():
                        pass                # yield below
                    else:
                        self.runner.finish()
                        self._settle_locked()
                        return
                finally:
                    for lk in reversed(locks):
                        lk.release()
        except (ValueError, ConnectionError, OSError, KeyError) as e:
            # helper died / push refused mid-round: park it — the next
            # reconcile re-plans the leftover names against the fresh
            # map (plan.remaining tracks exactly what didn't land)
            self.failed = True
            import errno as _errno
            if isinstance(e, OSError) and e.errno == _errno.ENOSPC:
                # r21: writeback hit a full store — same park contract
                # (cursors intact, the re-plan retries once space or a
                # better target shows up), but counted separately so
                # the capacity plane can see recovery being starved
                d.repair_policy._count("repair_enospc_parked")
            d.c.log(f"{d.name}: recovery round deferred: {e}")
            return
        sleep = float(d.config["osd_recovery_sleep"])
        if sleep > 0 and not d._stop.is_set():
            t = threading.Timer(sleep, self._requeue)
            t.daemon = True
            t.start()
        else:
            self._requeue()

    def _requeue(self) -> None:
        if self.d._stop.is_set():
            return
        self.d._sched_enqueue("background_recovery", self,
                              self.next_cost(), shard=self.shard())

    def _settle_locked(self) -> None:
        d = self.d
        d.suspect -= self.dead
        now_m = time.monotonic()
        for ps, _plan, _dead in self.entries:
            if d._recovering.get(ps) is self:
                d._recovering.pop(ps, None)
            # r17 exposure accounting: the stripe left m-1 when its
            # rebuild landed — close its time-at-m-1 interval
            d.repair_policy.note_exposure(ps, False, now=now_m)
            try:
                d._persist_meta(ps)
            except (ConnectionError, OSError, KeyError) as e:
                d.c.log(f"{d.name}: pg 1.{ps} post-recovery persist "
                        f"deferred: {e}")
        d.perf.inc("recovery_rounds")
        d._note_repair_gauges()


class _OpShard:
    """One op-queue shard (ref: OSD::ShardedOpWQ shard): its own
    mClock scheduler + condition + worker thread. Ops hash to a shard
    by PG id (OSDDaemon._shard_of), so one PG's ops drain FIFO on one
    worker — per-PG ordering needs no cross-shard coordination."""

    def __init__(self, daemon: "OSDDaemon", idx: int):
        from .scheduler import MClockScheduler
        self.d = daemon
        self.idx = idx
        self.sched = MClockScheduler(daemon._mclock_profiles())
        self.cv = threading.Condition()
        self._thread = threading.Thread(
            target=self._worker_loop, daemon=True,
            name=f"{daemon.name}-shard{idx}")

    def start(self) -> None:
        self._thread.start()

    def enqueue(self, cls: str, item, cost: float = 1.0) -> None:
        with self.cv:
            self.sched.enqueue(cls, item, cost)
            self.cv.notify()

    def _worker_loop(self) -> None:
        """Drain this shard's mClock queue in tag order. Every item is
        a callable; recovery rounds re-enqueue themselves after each
        batch grant, so a queued client op never waits behind more
        than ONE recovery batch (the p95-bounding property the
        scheduler exists for), and only within its own shard."""
        d = self.d
        while not d._stop.is_set():
            with self.cv:
                now = time.monotonic()
                got = self.sched.dequeue(now)
                if got is None:
                    nxt = self.sched.next_eligible(now)
                    self.cv.wait(
                        0.5 if nxt is None
                        else min(0.5, max(0.001, nxt - now)))
                    continue
            _cls, item = got
            d.perf.inc("op_shard_grants")
            try:
                item()
            except Exception as e:   # noqa: BLE001 — the worker must
                # survive any op; the item owns its own error reply
                d.c.log(f"{d.name}: op shard {self.idx} item "
                        f"failed: {e!r}")
            d._note_shard_gauges()


class _BatchJoin:
    """Reply assembly for a `batch` frame whose sub-ops span shards:
    each shard executes its slots FIFO (per-PG order holds), the LAST
    shard to finish encodes the reply in original slot order and
    sends it — one frame in, one frame out, exactly like the
    single-shard path."""

    def __init__(self, daemon: "OSDDaemon", peer: str, msg,
                 n_slots: int, n_groups: int,
                 t_enq: float | None = None):
        self.d, self.peer, self.msg = daemon, peer, msg
        self.slots: list = [None] * n_slots
        self._left = n_groups
        self._lock = threading.Lock()
        self.t_enq = t_enq

    def run(self, items: list) -> None:
        """items: [(slot, kind, body)] — one shard's share."""
        with self.d._trace_enter(self.msg, self.t_enq):
            self._run_inner(items)

    def _run_inner(self, items: list) -> None:
        for slot, kind, body in items:
            try:
                blob = self.d._one_client_op(self.peer, kind, body)
                self.slots[slot] = (True, blob, "")
            except Exception as err:   # noqa: BLE001 — per-sub-op
                # fault isolation (the client maps each slot back to
                # its op's retry state)
                self.slots[slot] = (False, b"",
                                    f"{type(err).__name__}:{err}")
        with self._lock:
            self._left -= 1
            done = self._left == 0
        if not done:
            return
        e = Encoder()
        e.u32(len(self.slots))
        for ok, blob, err in self.slots:
            e.boolean(ok).blob_ref(blob).string(err)
        try:
            self.d.msgr.send(self.peer, MOSDOpReply(
                self.msg.req_id, True, self.msg.kind, e.bytes()))
        except (KeyError, OSError, ConnectionError):
            pass


class OSDDaemon:
    """One OSD endpoint: local store + the PGs it primaries."""

    def __init__(self, osd_id: int, cluster: "StandaloneCluster"):
        self.osd_id = osd_id
        self.c = cluster
        self.name = f"osd.{osd_id}"
        self.store = cluster.make_store(osd_id)
        self.msgr = Messenger(self.name, secret=cluster.secret,
                              compress=cluster.compress,
                              workers=cluster.msgr_workers,
                              uds=cluster.msgr_uds)
        self.rpc = _Rpc(self.msgr, MStoreReply.type_id)
        self.osdmap: OSDMap | None = None
        self.backends: dict[int, object] = {}     # ps -> PGBackend
        # per-PG snapshot + object-class state; rides _persist_meta so
        # a primary takeover restores it with the rest of the PG
        self.snapsets: dict[int, dict[str, list]] = {}
        self.births: dict[int, dict[str, int]] = {}
        self.obj_kv: dict[int, dict[str, dict]] = {}
        # divergent names whose rewind was deferred (helpers not
        # reachable during the restoring reconcile); retried on every
        # later reconcile until clean
        self._rewind_pending: dict[int, set[str]] = {}
        self._restore_backoff: dict[int, float] = {}
        # per-PG delta-meta window: (entries since last full base
        # persist, base pg_log head) — see _meta_extra
        self._meta_delta: dict[int, tuple[list, int]] = {}
        # interval-freshness bookkeeping (the up_thru machinery, ref:
        # PeeringState WaitUpThru): per primaried pg, the map acting
        # we last processed and the epoch its interval began. While
        # osd_up_thru[self] lags an interval's start, that PG is
        # PRE-ACTIVE: no restore, no recovery, no client I/O — only a
        # MOSDAlive request to the monitors. The activation persist
        # (_persist_meta's epoch stamp) therefore happens strictly
        # AFTER the up_thru commit, which grounds the (epoch, head)
        # meta ranking in map-provable interval freshness: an interval
        # whose primary died pre-activation left neither an up_thru
        # claim nor an epoch-stamped blob, so later peering neither
        # waits on it nor trusts it.
        self._interval_start: dict[int, int] = {}
        self._last_acting: dict[int, list[int]] = {}
        # scheduled scrub bookkeeping (per primaried pg; ref: the
        # scrubber's per-PG schedule, osd_scrub_min_interval /
        # osd_deep_scrub_interval)
        self._last_scrub: dict[int, float] = {}
        self._last_deep: dict[int, float] = {}
        self.scrub_reports: dict[int, dict] = {}
        # per-daemon layered config (ref: md_config_t per daemon). The
        # cluster's tuned knobs act as the conf-file layer; the
        # centralized KV riding the committed OSDMap lands at the
        # "mon" layer on every map fold (_apply_central_config), so
        # the full precedence chain default < file < mon < override
        # is live on a running daemon and observers fire on commit.
        # Built BEFORE observability: the OpTracker resolves its
        # complaint/history thresholds through this config live.
        from ..utils.config import Config
        self.config = Config()
        self.config.load_file({
            "osd_heartbeat_interval": cluster.hb_interval,
            "osd_heartbeat_grace": cluster.hb_grace,
            "osd_op_num_shards": cluster.op_shards,
            "msgr_reactor_workers": cluster.msgr_workers,
        })
        self._cfg_applied: dict[str, str] = {}
        # admin-socket observability (ref: OpTracker/TrackedOp +
        # PerfCounters served by `ceph daemon osd.N <cmd>`)
        self._init_observability()
        self.suspect: set[int] = set()            # osd ids (local view)
        self._lock = threading.RLock()
        self._store_lock = threading.Lock()
        self._last_pong: dict[int, float] = {}
        # per-peer store-op round-trip EWMA (seconds): the repair
        # planner's per-helper read costs — suspects and slow peers
        # rank behind fast trusted ones instead of uniform-cost picks
        self._peer_lat: dict[int, float] = {}
        # CLIENT-observed per-osd latency (r15, the r14 follow-up):
        # sampled ops carry the client hedge ladder's EWMA/complaint
        # snapshot; folded here as osd -> (seconds, wall stamp) so
        # _helper_costs ranks by the slower of the daemon's own view
        # and what clients actually experienced. Stamped so a stale
        # client claim ages out instead of pinning costs forever.
        self._client_lat: dict[int, tuple[float, float]] = {}
        self._reported: set[int] = set()
        self._stop = threading.Event()
        # cephx (ref: OSD::ms_verify_authorizer): rotating secrets are
        # fetched at boot (stand-in: exported straight from the
        # cluster's KeyServer); per-peer sessions are established by
        # MAuthOp("authorize") and die with the process
        self._authed: dict[str, dict] = {}
        self.verifier = None
        self._cauth = None
        if cluster.key_server is not None:
            from ..auth import ServiceVerifier
            self.verifier = ServiceVerifier(
                "osd", cluster.key_server.export_rotating("osd"))
        self._start()

    def _start(self) -> None:
        """Register handlers + start the heartbeat thread (shared by
        __init__ and revive so the two can't silently diverge)."""
        # the daemon's live admin socket (ref: admin_socket.cc asok
        # per daemon): same dispatcher as the wire `admin` op, but
        # reachable without a client, a map, or cephx — the operator's
        # side door into a wedged daemon
        # mClock-governed SHARDED op admission (ref: src/osd/
        # scheduler/mClockScheduler.cc wired into OSD::op_shardedwq
        # with osd_op_num_shards shards): client ops and recovery
        # batch grants hash by PG id to a shard; each shard drains its
        # own scheduler in tag order on its own worker — per-PG
        # ordering is a queue invariant (one PG, one shard, one FIFO)
        # while independent PGs dispatch concurrently, and
        # background_recovery competes with (instead of head-of-line-
        # blocking) the client ops of its shard. Built fresh here
        # (empty queues per boot), and BEFORE any handler registers —
        # a map or op frame may land the moment the messenger knows
        # the type. mClock reservations are PER SHARD (the
        # reference's documented osd_op_num_shards caveat).
        self.num_op_shards = max(1, int(
            self.config["osd_op_num_shards"]))
        self.op_shards = [_OpShard(self, i)
                          for i in range(self.num_op_shards)]
        # compat alias: shard 0's scheduler (single-shard daemons
        # behave exactly like the pre-shard tree)
        self.op_sched = self.op_shards[0].sched
        self._sched_cv = self.op_shards[0].cv
        # per-PG execution locks: client ops serialize within their
        # PG only; reconcile/recovery take the PG locks of the PGs
        # they mutate (always AFTER self._lock — one global order)
        self._pg_locks: dict[int, threading.RLock] = {}
        self._pg_locks_guard = threading.Lock()
        self._recovering: dict[int, "_RecoveryRound"] = {}
        # r21: PGs whose rebuild is parked because a replacement
        # target sits at/over backfillfull (one counter tick per
        # park transition, not per reconcile beat)
        self._bff_parked: set[int] = set()
        # r17 repair policy plane: per-peer DownClocks + parked
        # rebuilds + exposure accounting, and the per-failure-domain
        # repair token buckets. Built per boot (in-RAM policy state
        # dies with the process — a restarted primary is eager about
        # peers whose down window it cannot date; see
        # RepairPolicy.observe_map).
        from .repairpolicy import RepairPolicy
        from .scheduler import DomainBudgets
        self.repair_policy = RepairPolicy(config=self.config,
                                          perf=self.perf,
                                          now_fn=time.monotonic)
        self.domain_budgets = DomainBudgets()
        for sh in self.op_shards:
            sh.start()
        from ..utils.admin_socket import AdminSocket
        self.asok = AdminSocket(self.c.asok_path(self.name))
        for _cmd in self._ADMIN_CMDS:
            self.asok.register(_cmd,
                               lambda args, c=_cmd:
                               self._admin_obj((c + " " + args).strip()))
        self.asok.start()
        m = self.msgr
        m.register_handler(MStoreOp.type_id, self._on_store_op)
        m.register_handler(MOSDOp.type_id, self._on_client_op)
        m.register_handler(MOSDPing.type_id, self._on_ping)
        m.register_handler(MOSDPingReply.type_id, self._on_pong)
        # map folds run a full reconcile (meta gathers, shard moves —
        # BLOCKING remote rpc): queued dispatch, never on a reactor,
        # or the fold would deadlock against its own replies
        m.register_handler(MOSDMapMsg.type_id, self._on_map,
                           fast=False)
        m.register_handler(MOSDIncMapMsg.type_id, self._on_inc_map,
                           fast=False)
        if self.verifier is not None:
            from ..auth import ClientAuth
            m.register_handler(MAuthOp.type_id, self._on_auth)
            # this daemon's own principal, for osd->osd store traffic
            # (sessions and rpc die with the process: built in _start
            # so a revive gets fresh ones)
            self.auth_rpc = _Rpc(self.msgr, MAuthReply.type_id)
            self._cauth = ClientAuth(
                _WireAuth(self.c, self.auth_rpc), self.name,
                self.c.osd_secrets[self.osd_id])
            # single-flight background ticket refresher: dispatch-path
            # authorize (the meta gather, the shard fan-out) must
            # NEVER hunt monitors itself — see _authorize_peer
            self._ticket_gate = threading.Lock()
            # pre-warm tickets OFF the dispatch path: peer store reads
            # happen inside map/op dispatch, and a monitor hunt there
            # (seconds, worse across a partition) stalls the dispatch
            # thread — pings queue up behind it and peers mark this
            # daemon down, cascading into fake failures. The reference
            # likewise fetches rotating secrets/tickets on its own
            # monc thread, not in fast dispatch.
            def _prewarm():
                for _ in range(10):
                    if self._stop.is_set():
                        return
                    try:
                        self._cauth.fetch_tickets(["osd"])
                        return
                    except Exception:   # noqa: BLE001 — mons booting
                        self._stop.wait(0.5)
            threading.Thread(target=_prewarm, daemon=True).start()
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    daemon=True)
        self._hb.start()

    def _spawn_ticket_refresh(self) -> None:
        """Kick ONE background fetch_tickets (no-op when one is
        already running). Dispatch threads call this instead of
        fetching inline — the deferral costs one reconcile retry,
        the inline hunt can cost the whole daemon (see
        _authorize_peer)."""
        if not self._ticket_gate.acquire(blocking=False):
            # single-flight: someone is already fetching — this wait
            # is the cheap outcome the counter exists to prove
            self.perf.inc("cephx_refresh_coalesced")
            return
        self.perf.inc("cephx_refresh_kicked")

        def _go():
            try:
                self._cauth.fetch_tickets(["osd"])
            except Exception:    # noqa: BLE001 — mons down/partition:
                pass             # the next deferral re-kicks us
            finally:
                self._ticket_gate.release()
        threading.Thread(target=_go, daemon=True).start()

    def _authorize_peer(self, peer: str) -> None:
        """osd->osd cephx (ref: OSD heartbeat/cluster messengers carry
        cephx authorizers too): used by RemoteStore on first contact.

        Runs on DISPATCH threads (the meta gather inside _on_map, the
        write fan-out inside _on_client_op) while self._lock is held —
        so it must never hunt monitors: the monitor's auth reply can
        be head-of-line-blocked behind an undelivered map frame on
        the same connection, whose reader is waiting for self._lock.
        Under a map-commit storm (boot, up_thru activation rounds)
        that livelocks the whole daemon. Cold cache -> fail fast,
        refresh in the background, let the reconcile retry."""
        if not self._cauth.has_ticket("osd"):
            self.perf.inc("authorize_deferred")
            self._spawn_ticket_refresh()
            raise ConnectionError(
                f"{self.name}: osd service ticket not warm; authorize "
                f"to {peer} deferred (background refresh kicked)")
        _wire_authorize(self._cauth, self.auth_rpc, peer, "osd",
                        async_refresh=self._spawn_ticket_refresh)

    # -- mClock op admission -------------------------------------------------

    # the reference's built-in profile split (osd_mclock_profile):
    # (reservation, weight, limit) per class, ops/s-space with cost
    # scaled so one recovery batch counts its bytes, not "one op"
    _MCLOCK_BUILTIN = {
        "high_client_ops": {
            "client": (50.0, 10.0, 0.0),
            "background_recovery": (25.0, 5.0, 100.0),
            "background_best_effort": (0.0, 2.0, 0.0),
            "scrub": (0.0, 1.0, 50.0)},
        "balanced": {
            "client": (50.0, 5.0, 0.0),
            "background_recovery": (50.0, 5.0, 150.0),
            "background_best_effort": (0.0, 2.0, 0.0),
            "scrub": (0.0, 1.0, 50.0)},
        "high_recovery_ops": {
            "client": (30.0, 2.0, 0.0),
            "background_recovery": (60.0, 10.0, 0.0),
            "background_best_effort": (0.0, 2.0, 0.0),
            "scrub": (0.0, 1.0, 50.0)},
    }

    #: per-tenant class namespace inside the scheduler — one class per
    #: client entity, so heavy tenants (and their hedged duplicates)
    #: compete under their own (ρ, w, λ) tags
    _TENANT_CLS = "tenant:"

    def _mclock_profiles(self) -> dict:
        """(ρ, w, λ) per op class, resolved LIVE through this daemon's
        layered config: osd_mclock_profile picks a built-in split;
        `custom` reads the osd_mclock_scheduler_* knobs (the reference's
        config-change path, no restart)."""
        from .scheduler import ClientProfile
        name = str(self.config["osd_mclock_profile"])
        if name == "custom":
            cfg = self.config
            table = {
                "client": (cfg["osd_mclock_scheduler_client_res"],
                           cfg["osd_mclock_scheduler_client_wgt"],
                           cfg["osd_mclock_scheduler_client_lim"]),
                "background_recovery": (
                    cfg["osd_mclock_scheduler_background_recovery_res"],
                    cfg["osd_mclock_scheduler_background_recovery_wgt"],
                    cfg["osd_mclock_scheduler_background_recovery_lim"]),
                "background_best_effort": (0.0, 2.0, 0.0),
                "scrub": (0.0, 1.0, 50.0)}
        else:
            table = self._MCLOCK_BUILTIN.get(
                name, self._MCLOCK_BUILTIN["high_client_ops"])
        return {cls: ClientProfile(reservation=r, weight=w, limit=lim)
                for cls, (r, w, lim) in table.items()}

    def _tenant_profile(self, entity: str):
        """Resolve one client entity's (ρ, w, λ): the per-entity table
        first, then the tenant default, then the aggregate client
        class split (equal-share per entity). All three resolve LIVE
        through config, so `ceph config set
        osd_mclock_scheduler_tenant_profiles ...` retunes a running
        daemon's tenants on the next fold."""
        from .scheduler import parse_profile, parse_profile_table
        try:
            table = parse_profile_table(
                self.config["osd_mclock_scheduler_tenant_profiles"])
            if entity in table:
                return table[entity]
            dflt = str(
                self.config["osd_mclock_scheduler_tenant_default"]
            ).strip()
            if dflt:
                return parse_profile(dflt)
        except (KeyError, ValueError) as e:
            self.c.log(f"{self.name}: bad tenant QoS config ignored: "
                       f"{e}")
        return self._mclock_profiles()["client"]

    def _client_class(self, peer: str, shard: "_OpShard") -> str:
        """mClock class of one client op: per-tenant, keyed by the
        cephx entity bound to the peer's session (the authenticated
        identity; caps already gated it) — the transport peer name
        without cephx. Registers the class on first contact with the
        op's shard (each shard tags its own tenants)."""
        sess = self._authed.get(peer)
        entity = sess["entity"] if sess is not None else peer
        cls = self._TENANT_CLS + entity
        with shard.cv:
            shard.sched.ensure_class(cls, self._tenant_profile(entity))
        return cls

    def _refresh_mclock_profiles(self) -> None:
        """Re-resolve the (ρ, w, λ) table after a config change (called
        from the central-config fold — cheaper and lifetime-safer than
        per-key observers across revives). Live per-tenant classes are
        re-resolved too, on every shard."""
        try:
            profiles = self._mclock_profiles()
        except (KeyError, ValueError) as e:
            self.c.log(f"{self.name}: bad mclock config ignored: {e}")
            return
        for sh in self.op_shards:
            with sh.cv:
                for cls, prof in profiles.items():
                    q = sh.sched._classes.get(cls)
                    if q is not None and q.profile != prof:
                        sh.sched.set_profile(cls, prof)
                for cls in sh.sched.class_names():
                    if cls.startswith(self._TENANT_CLS):
                        entity = cls[len(self._TENANT_CLS):]
                        sh.sched.ensure_class(
                            cls, self._tenant_profile(entity))

    # -- shard routing --------------------------------------------------------

    def _shard_of(self, ps: int) -> "_OpShard":
        """PG -> shard (the OSD::ShardedOpWQ hash): stable for the
        daemon's lifetime, so one PG's ops always drain FIFO on one
        worker."""
        return self.op_shards[ps % self.num_op_shards]

    @staticmethod
    def _op_ps(body) -> int:
        """Peek the PG id every client-op body leads with (the
        Encoder's raw little-endian u32) without a full decode."""
        try:
            return struct.unpack_from("<I", body, 0)[0]
        except struct.error:
            return 0

    def _pg_lock(self, ps: int) -> threading.RLock:
        with self._pg_locks_guard:
            lk = self._pg_locks.get(ps)
            if lk is None:
                lk = self._pg_locks[ps] = threading.RLock()
            return lk

    def _sched_enqueue(self, cls: str, item, cost: float = 1.0,
                       shard: "_OpShard | None" = None) -> None:
        (shard or self.op_shards[0]).enqueue(cls, item, cost)
        self._note_shard_gauges()

    def _note_shard_gauges(self) -> None:
        """Declared occupancy gauges over the shard set: total queued
        depth + grant imbalance (max-min served across shards — the
        hash-skew signal the bench JSON carries)."""
        depths = [len(sh.sched) for sh in self.op_shards]
        served = [sum(q.served for q in sh.sched._classes.values())
                  for sh in self.op_shards]
        self.perf.set("op_shard_depth", sum(depths))
        self.perf.set("op_shard_imbalance",
                      max(served) - min(served) if served else 0)

    def shard_dump(self) -> dict:
        """Per-shard scheduler occupancy (the `dump_op_shards` admin
        view; rados_bench ships it as per-shard attribution)."""
        return {f"shard_{sh.idx}": sh.sched.dump()
                for sh in self.op_shards}

    def sched_dump(self) -> dict:
        """Class -> occupancy MERGED across shards (the pre-shard
        `dump_mclock` shape: tools and tests iterate class names at
        the top level)."""
        out: dict = {}
        for sh in self.op_shards:
            for cls, row in sh.sched.dump().items():
                cur = out.get(cls)
                if cur is None:
                    out[cls] = dict(row)
                else:
                    cur["queued"] += row["queued"]
                    cur["served"] += row["served"]
                    cur["served_cost"] = round(
                        cur["served_cost"] + row["served_cost"], 3)
                    cur["throttled"] += row.get("throttled", 0)
        return out

    # -- store service (the SubOp executor) ---------------------------------

    _STORE_READ_KINDS = frozenset(
        {"read", "readv", "readv_ranges", "rmw_fetch", "stat",
         "getattr", "exists", "ls", "omap_get", "omap_iter",
         "retro_publish"})

    def _on_store_op(self, peer: str, msg: MStoreOp) -> None:
        # the store plane is ticket-gated exactly like the client op
        # plane — without this, MOSDOp's EPERM gate would be decorative
        # (any peer could reach shard bytes via raw MStoreOp frames)
        if self.verifier is not None:
            deny = self._auth_gate(
                peer,
                "r" if msg.kind in self._STORE_READ_KINDS else "w")
            if deny is not None:
                try:
                    self.msgr.send(peer, MStoreReply(
                        msg.req_id, False, msg.kind, err=deny))
                except (KeyError, OSError, ConnectionError):
                    pass
                return
        try:
            # r18: retro span publication is a daemon-level command,
            # not a store op — answer before the store lock
            if msg.kind == "retro_publish":
                d = Decoder(msg.blob)
                e = Encoder()
                e.u32(self._retro_publish(d.u64()))
                rep = MStoreReply(msg.req_id, True, msg.kind,
                                  e.bytes())
                try:
                    self.msgr.send(peer, rep)
                except (KeyError, OSError, ConnectionError):
                    pass
                return
            # r15: a sampled context on the frame puts this hop's
            # spans under the originating trace — osd.subop covers the
            # whole service (store-lock wait + reply encode), with the
            # store apply itself a nested child, so the assembler can
            # split store time from sub-op queueing.
            from ..utils.flight_recorder import activate, trace_span
            ctx = msg.trace if msg.trace is not None \
                and msg.trace.sampled else None
            t0w, t0 = time.time(), time.perf_counter()
            apply_s = 0.0
            with activate(ctx, self.flight if ctx is not None
                          else None):
                with trace_span("osd.subop", kind=msg.kind):
                    with self.perf.time("subop_latency"):
                        with self._store_lock:
                            ta = time.perf_counter()
                            with trace_span("store.apply"):
                                blob = self._store_op(msg.kind,
                                                      msg.blob)
                            apply_s = time.perf_counter() - ta
            # r18: an UNSAMPLED context still carries the trace id —
            # remember this hop's window so a later slow-op retro
            # assembly covers the replica too (the sampled case
            # already recorded eagerly above)
            if msg.trace is not None and not msg.trace.sampled:
                self._subop_note(msg.trace, msg.kind, t0w,
                                 time.perf_counter() - t0, apply_s)
            self.perf.inc_many((("subop", 1),
                                ("subop_in_bytes", len(msg.blob)),
                                ("subop_out_bytes", len(blob))))
            rep = MStoreReply(msg.req_id, True, msg.kind, blob)
        except KeyError as e:
            rep = MStoreReply(msg.req_id, False, msg.kind,
                              err=f"KeyError:{e}")
        except Exception as e:   # noqa: BLE001 — fault isolation: the
            # daemon must answer, not die, on a bad op
            rep = MStoreReply(msg.req_id, False, msg.kind,
                              err=f"{type(e).__name__}:{e}")
        try:
            self.msgr.send(peer, rep)
        except (KeyError, OSError, ConnectionError):
            pass                 # requester died; nothing to tell

    def _store_op(self, kind: str, body: bytes) -> bytes:
        st = self.store
        if kind == "txn":
            st.queue_transaction(_decode_txn(body))
            return b""
        d = Decoder(body)
        cid, oid = d.string(), d.string()
        if kind == "read":
            off, ln = d.i64(), d.i64()
            arr = st.read(cid, oid, off, None if ln < 0 else ln)
            return arr.tobytes()
        if kind == "readv":
            # multi-object shard fetch: ONE frame returns many equal-
            # length rows (+ their hinfo attrs) — the recovery pull
            # unit (ref: MOSDPGPull carrying a PullOp vector; the
            # per-object read() path costs B round trips per helper
            # shard per batch)
            attr_key = d.string()
            length = d.i64()
            names = d.list(Decoder.string)
            rows = []
            for name in names:
                arr = st.read(cid, name)
                if len(arr) != length:
                    # a stale/partial shard must fail LOUDLY — zero-
                    # filling would hand the decoder garbage that
                    # writeback then stamps with matching CRCs
                    raise ValueError(
                        f"readv: {name!r} is {len(arr)} bytes, "
                        f"expected {length}")
                rows.append(np.asarray(arr, np.uint8))
            e = Encoder()
            e.blob(b"".join(r.tobytes() for r in rows))
            e.list([st.getattr(cid, n, attr_key) for n in names]
                   if attr_key else [], Encoder.blob)
            return e.bytes()
        if kind == "readv_ranges":
            # sub-chunk shard fetch (repair-locality planner): ship
            # only the planned (offset, length) ranges of every row.
            # The full-row hinfo verify + range CRCs happen HERE at
            # the source (readv_ranges_host) — the receiver fold-
            # verifies the shipped bytes and plans around any row the
            # source flagged rotten.
            from .ecbackend import readv_ranges_host
            attr_key = d.string()
            length = d.i64()
            ranges = d.list(lambda dd: (dd.i64(), dd.i64()))
            names = d.list(Decoder.string)
            rows, crcs, bad = readv_ranges_host(
                st, cid, names, length, ranges, attr_key or None)
            e = Encoder()
            e.blob(rows.tobytes())
            e.list([int(c) for c in crcs] if crcs is not None else [],
                   Encoder.u32)
            e.list([int(b) for b in bad], Encoder.u32)
            return e.bytes()
        if kind == "rmw_fetch":
            # combined RMW prepare fetch (r17): per delta job, the
            # hinfo attr (present flag + bytes) and the touched
            # pre-image sub-ranges, in ONE frame per participant
            # shard — the reply mirrors the item order. A short read
            # (write past the old tail) returns the short bytes; the
            # receiver zero-pads, exactly like the old per-span read.
            attr_key = d.string()
            items = d.list(lambda dd: (
                dd.string(), dd.list(lambda d2: (d2.i64(), d2.i64()))))
            e = Encoder()

            def one(en: Encoder, item) -> None:
                name, ranges = item
                try:
                    attr, ok = st.getattr(cid, name, attr_key), True
                except KeyError:
                    attr, ok = b"", False
                en.boolean(ok).blob(attr)
                en.list([np.asarray(st.read(cid, name, off, ln),
                                    np.uint8).tobytes()
                         for off, ln in ranges], Encoder.blob)
            e.list(items, one)
            return e.bytes()
        if kind == "stat":
            return Encoder().i64(st.stat(cid, oid)).bytes()
        if kind == "getattr":
            return st.getattr(cid, oid, d.string())
        if kind == "exists":
            return b"\x01" if st.exists(cid, oid) else b"\x00"
        if kind == "ls":
            return Encoder().list(st.list_objects(cid),
                                  Encoder.string).bytes()
        if kind == "omap_get":
            key = d.blob()
            obj = st.collections[cid].get(oid)
            if obj is None or key not in obj.omap:
                raise KeyError(f"{cid}/{oid}:{key!r}")
            return obj.omap[key]
        if kind == "omap_iter":
            has_start = d.boolean()
            start = d.blob()
            limit = d.i64()
            page = st.omap_iter(cid, oid,
                                start_after=start if has_start else None,
                                limit=None if limit < 0 else limit)
            e = Encoder()
            e.list(page, lambda en, kv: en.blob(kv[0]).blob(kv[1]))
            return e.bytes()
        raise ValueError(f"unknown store op {kind!r}")

    # -- PG hosting ----------------------------------------------------------

    def _shard_set(self) -> ShardSet:
        def factory(osd_id: int):
            if osd_id == self.osd_id:
                return self.store
            return RemoteStore(self.rpc, f"osd.{osd_id}",
                               timeout=self.c.op_timeout,
                               authorize=self._authorize_peer
                               if self.verifier is not None else None,
                               on_latency=self._note_peer_latency)
        return ShardSet(store_factory=factory)

    def _note_peer_latency(self, peer: str, dt: float) -> None:
        """Fold one store-op round trip into the peer's latency EWMA
        (the r11 client ladder's 0.75/0.25 blend, daemon-side)."""
        if not peer.startswith("osd."):
            return
        osd = int(peer[4:])
        prev = self._peer_lat.get(osd)
        self._peer_lat[osd] = dt if prev is None \
            else 0.75 * prev + 0.25 * dt
        # r22: the same sample feeds the link plane's "store" channel
        # (wire + service time, vs the hb channel's wire + dispatch)
        if bool(self.config["osd_network_observability"]):
            self.link_tracker.note(peer, dt, channel="store")

    #: client-observed latency claims older than this are ignored (a
    #: one-off slow window must not bias helper picks for hours)
    _CLIENT_LAT_TTL = 30.0

    def _note_client_costs(self, ctx) -> None:
        """Fold a sampled op's client cost snapshot (per-osd read
        EWMAs + the client's live complaint set) into this daemon's
        helper cost table. Complaints fold as a 1s-equivalent floor —
        well above any healthy round trip, well below the down
        surcharge — so a client-suspected helper ranks last among the
        live ones without being treated as dead."""
        now = time.monotonic()
        for osd, lat in (ctx.client_lat or {}).items():
            osd = int(osd)
            prev = self._client_lat.get(osd)
            blend = float(lat) if prev is None \
                else 0.75 * prev[0] + 0.25 * float(lat)
            self._client_lat[osd] = (blend, now)
        for osd in ctx.client_suspects:
            cur = self._client_lat.get(int(osd))
            base = cur[0] if cur is not None else 0.0
            self._client_lat[int(osd)] = (max(base, 1.0), now)

    def _helper_costs(self, be) -> dict[int, int]:
        """Per-slot read costs for the repair-locality planner
        (minimum_to_decode_with_cost units: integer microseconds).
        Real signals, not uniform guesses: the peer-latency EWMA from
        actual store-op round trips, the CLIENT-observed EWMAs sampled
        ops shipped (r15 — the slower of the two views wins, so a
        helper that answers its peers fast but stalls clients still
        ranks behind), plus a prohibitive surcharge for anyone in the
        down/slow complaint memory — such slots are usually excluded
        outright, but a cost keeps ties deterministic when they must
        serve."""
        n_osds = len(self.osdmap.osd_up) if self.osdmap is not None \
            else 0
        now = time.monotonic()
        costs: dict[int, int] = {}
        for s, osd in enumerate(be.acting):
            if osd == self.osd_id:
                cost = 0                  # our own store is free
            else:
                lat = self._peer_lat.get(osd, 0.001)
                claim = self._client_lat.get(osd)
                if claim is not None \
                        and now - claim[1] < self._CLIENT_LAT_TTL:
                    lat = max(lat, claim[0])
                # r22 link-cost feed: the heartbeat-RTT EWMA toward
                # this helper joins the blend — slowest view wins, so
                # a degraded WIRE ranks a helper down even while its
                # store answers the few ops that do arrive quickly
                hb = self.link_tracker.ewma_s(f"osd.{osd}")
                if hb > lat:
                    lat = hb
                    self.perf.inc("net_helper_penalties")
                cost = int(lat * 1e6)
            if osd in self.suspect or (
                    _valid_osd(osd, n_osds)
                    and self.osdmap is not None
                    and not self.osdmap.osd_up[osd]):
                cost += 1_000_000_000
            costs[s] = cost
        return costs

    def _acting(self, ps: int) -> list[int]:
        return self.osdmap.pg_to_up_acting_osds(1, ps)[2]

    def _make_backend(self, ps: int, acting: list[int],
                      ensure_collections: bool = True):
        if self.c.is_erasure:
            return ECBackend(self.c.profile, f"1.{ps}", acting,
                             self._shard_set(),
                             chunk_size=self.c.chunk_size,
                             perf=self.ec_perf,
                             ensure_collections=ensure_collections)
        return ReplicatedBackend(self.c.pool_size, f"1.{ps}", acting,
                                 self._shard_set(),
                                 min_size=self.c.pool_min_size,
                                 ensure_collections=ensure_collections)

    def _persist_meta(self, ps: int) -> None:
        """Ship the PG's FULL metadata to every live shard as omap
        (the pg_log-rides-with-the-transaction discipline, ref: PGLog
        entries inside ObjectStore::Transaction). Clears the delta key
        in the same transaction — the base subsumes it (see
        _meta_extra for the delta scheme)."""
        be = self.backends[ps]
        blob = self._encode_meta(ps)
        self._meta_delta[ps] = ([], be.pg_log.head)
        # fan the omap txns out PIPELINED: transmit to every live
        # shard first, then wait each ack — one overlapped round trip
        # instead of len(acting) sequential ones (failure handling
        # unchanged: an unreachable shard is suspected, not fatal)
        waits: list[tuple[int, object]] = []
        for s, osd in enumerate(be.acting):
            if osd in self.suspect:
                continue
            t = Transaction().omap_set(shard_cid(be.pg, s), "__pg_meta__",
                                       {PG_META_KEY: blob,
                                        PG_META_DELTA_KEY: b""})
            st = be.cluster.osd(osd)
            submit = getattr(st, "queue_transaction_async", None)
            try:
                if submit is not None:
                    waits.append((osd, submit(t)))
                else:
                    st.queue_transaction(t)
            except (ConnectionError, OSError):
                self.suspect.add(osd)
        for osd, h in waits:
            try:
                h.result()
            except (ConnectionError, OSError):
                self.suspect.add(osd)

    def _encode_meta_delta(self, ps: int) -> bytes:
        """The bounded per-write metadata record: entries appended
        since the last FULL base persist, plus the current applied
        cursors. O(delta window) per write where the base blob is
        O(objects in PG) — the difference between a flat and a
        quadratically-degrading write path at scale. `base_head` pins
        which base the delta extends; a reader ignores a delta whose
        base doesn't match (defensive — the clearing txn makes the
        pair atomic per shard)."""
        be = self.backends[ps]
        entries, base_head = self._meta_delta[ps]
        e = Encoder()
        e.start(1, 1)
        e.u64(self.osdmap.epoch if self.osdmap is not None else 0)
        e.u64(base_head)
        e.list(be.shard_applied, lambda en, v: en.u64(v))
        e.list(entries, lambda en, t: en.string(t[0]).u64(t[1])
               .u64(t[2]))
        e.finish()
        return e.bytes()

    @staticmethod
    def _decode_meta_delta(blob: bytes):
        """-> (epoch, base_head, shard_applied, [(name, ver, size)])
        or None for an absent/corrupt delta."""
        if not blob:
            return None
        try:
            d = Decoder(blob)
            d.start(1)
            epoch = d.u64()
            base_head = d.u64()
            applied = d.list(Decoder.u64)
            entries = d.list(lambda dd: (dd.string(), dd.u64(),
                                         dd.u64()))
            d.finish()
        except Exception:        # noqa: BLE001 — corrupt delta: the
            return None          # base alone is still a candidate
        return (epoch, base_head, applied, entries)

    def _encode_meta(self, ps: int) -> bytes:
        """v4 envelope: the v3 body, zlib-wrapped. The blob ships to
        every live shard on EVERY write (it rides the write fan-out
        txn) and grows with the PG's object count — deflating the
        name/int-table body ~4-5x keeps the metadata bytes a small
        fraction of the data bytes at bench scale. compat=4: the
        body layout moved, so a pre-v4 reader must refuse (its
        _meta_rank treats the refusal as no-candidate) rather than
        misparse."""
        import zlib
        inner = self._encode_meta_v3(ps)
        e = Encoder()
        e.start(4, 4).blob(zlib.compress(inner, 1)).finish()
        return e.bytes()

    @staticmethod
    def _meta_decoder(blob: bytes) -> tuple[Decoder, int]:
        """Open a persisted meta blob, unwrapping the v4 zlib envelope
        when present; returns (decoder positioned at the v3-era
        fields, version<=3). Raises on corrupt/unknown blobs — every
        caller already treats decode failure as 'no candidate'."""
        d = Decoder(blob)
        v = d.start(4)
        if v >= 4:
            import zlib
            d = Decoder(zlib.decompress(d.blob()))
            v = d.start(3)
        return d, v

    def _encode_meta_v3(self, ps: int) -> bytes:
        import json as _json
        be = self.backends[ps]
        e = Encoder()
        # v2 appends snapsets/births/cls-kv (compat 1: a v1 reader
        # skips the tail via the section length); v3 leads with the
        # map epoch the blob was persisted under — takeover precedence
        # is (epoch, head), NOT bare head, so a revived ex-primary's
        # divergent log from an older interval can never win peering
        # (ref: PeeringState find_best_info's last_epoch_started
        # precedence)
        e.start(3, 1)
        e.u64(self.osdmap.epoch if self.osdmap is not None else 0)
        e.mapping(be.object_sizes, Encoder.string,
                  lambda en, v: en.u64(v))
        e.mapping(be.object_versions, Encoder.string,
                  lambda en, v: en.u64(v))
        e.blob(be.pg_log.encode())
        e.list(be.shard_applied, lambda en, v: en.u64(v))
        e.list(be.acting, lambda en, v: en.i32(v))
        e.mapping(self.snapsets.get(ps, {}), Encoder.string,
                  lambda en, v: en.list(
                      v, lambda e2, t: e2.u64(t[0]).u64(t[1])))
        e.mapping(self.births.get(ps, {}), Encoder.string,
                  lambda en, v: en.u64(v))
        e.mapping(self.obj_kv.get(ps, {}), Encoder.string,
                  lambda en, v: en.blob(
                      _json.dumps(v, sort_keys=True).encode()))
        e.finish()
        return e.bytes()

    @staticmethod
    def _meta_rank(pair) -> tuple[int, int] | None:
        """(epoch, head) precedence key of a persisted (base, delta)
        meta pair, or None for a corrupt candidate. Epoch FIRST: a
        newer interval's state beats any head from an older one — the
        divergent-log guard (ref: find_best_info). A delta extending
        this base advances the effective head (and carries the newer
        persist epoch); a delta pinned to a DIFFERENT base head is
        stale pairing and is ignored."""
        base, delta_blob = pair
        try:
            d, v = OSDDaemon._meta_decoder(base)
            epoch = d.u64() if v >= 3 else 0
            d.mapping(Decoder.string, Decoder.u64)
            d.mapping(Decoder.string, Decoder.u64)
            head = PGLog.decode(d.blob()).head
        except Exception:        # noqa: BLE001 — a corrupt candidate
            return None          # must not block takeover
        delta = OSDDaemon._decode_meta_delta(delta_blob) \
            if delta_blob else None
        if delta is not None and delta[1] == head and delta[3]:
            epoch = max(epoch, delta[0])
            head = max(head, delta[3][-1][1])
        return (epoch, head)

    def _load_meta(self, ps: int, acting: list[int],
                   suspect_extra: set[int] | None = None
                   ) -> tuple[bytes | None, bytes | None, bool]:
        """Find the FRESHEST persisted PG metadata: gather the blob
        from the local shard AND every reachable acting member, decode
        each, and keep the one with the highest (epoch, head) — a
        local copy can be stale (skipped by _persist_meta while
        transiently suspect) or DIVERGENT (this daemon died holding
        writes that never committed; bare-head precedence would
        resurrect them). Returns (best, best_local, quorum_ok): the
        local winner rides along so the caller can rewind divergent
        local entries against the authoritative log; quorum_ok says a
        MAJORITY of the up acting members answered the gather —
        restoring from fewer (only our own blob, peers not answering
        yet after a revive) could adopt a divergent dead-interval log
        as authoritative (ref: PeeringState GetInfo needs a quorum
        before the PG may go active)."""
        pgid = f"1.{ps}"
        # suspect_extra: callers' dead-peer hints (a degraded read's
        # routed-around primary) — skipped like suspects, but NEVER
        # recorded into self.suspect (the hint is per-op and untrusted)
        skip = set(self.suspect) | (suspect_extra or set())
        local_blobs: list[tuple[bytes, bytes | None]] = []
        remote_blobs: list[tuple[bytes, bytes | None]] = []
        heard = {self.osd_id}
        for s in range(len(acting)):
            obj = self.store.collections.get(
                shard_cid(pgid, s), {}).get("__pg_meta__")
            if obj is not None and PG_META_KEY in obj.omap:
                local_blobs.append(
                    (obj.omap[PG_META_KEY],
                     obj.omap.get(PG_META_DELTA_KEY)))
        n_osds = len(self.osdmap.osd_up) if self.osdmap is not None \
            else 0
        for osd in dict.fromkeys(acting):   # each peer once, in order
            if osd == self.osd_id or osd in skip \
                    or not _valid_osd(osd, n_osds):
                continue
            rs = RemoteStore(
                self.rpc, f"osd.{osd}", timeout=1.0,
                authorize=self._authorize_peer
                if self.verifier is not None else None)
            # a previous interval may have slotted this peer anywhere:
            # ask for EVERY slot's blob, not just the one our acting
            # assigns it (a slot-addressed miss reads as "no blob" and
            # silently crowns a divergent local log)
            for s in range(len(acting)):
                try:
                    base = rs.omap_get(shard_cid(pgid, s),
                                       "__pg_meta__", PG_META_KEY)
                    heard.add(osd)
                    try:
                        delta = rs.omap_get(shard_cid(pgid, s),
                                            "__pg_meta__",
                                            PG_META_DELTA_KEY)
                    except KeyError:
                        delta = None   # base-only shard (pre-delta)
                    remote_blobs.append((base, delta))
                except KeyError:
                    heard.add(osd)   # answered: no blob at this slot
                except (ConnectionError, OSError):
                    # unreachable: SUSPECT it (the store-op failure
                    # convention) so the next gather skips it instead
                    # of re-paying the timeout — an unpartitioned
                    # reconcile must never be starved by timeout loops
                    # against partitioned peers (that starves the
                    # heartbeat thread and stalls failure detection)
                    self.suspect.add(osd)
                    break

        def pick(pairs):
            best, best_rank = None, (-1, -1)
            for pair in pairs:
                rank = self._meta_rank(pair)
                if rank is not None and rank > best_rank:
                    best, best_rank = pair, rank
            return best

        up_members = {o for o in acting
                      if _valid_osd(o, n_osds)
                      and (o == self.osd_id or self.osdmap.osd_up[o])}
        need = len(up_members) // 2 + 1
        quorum_ok = len(heard & up_members) >= need
        if not quorum_ok:
            # the gather starved below quorum: clear the suspicion on
            # map-up members so the backoff retry RE-PROBES them
            # instead of skipping them forever. A suspicion set during
            # the boot thundering-herd (every daemon gathering from
            # every other at once, cold secure sessions) would
            # otherwise wedge this restore permanently once map
            # traffic goes quiet — we are not serving anyway, so
            # re-paying the probe timeout is the right price.
            self.suspect -= {o for o in up_members if o != self.osd_id}
        best_local = pick(local_blobs)
        # remotes first: on an (epoch, head) TIE the majority side
        # must win, never this daemon's own (possibly divergent) copy
        best = pick(remote_blobs + local_blobs)
        return best, best_local, quorum_ok

    @staticmethod
    def _apply_meta_delta(delta_blob, sizes: dict, versions: dict,
                          log: PGLog, applied: list) -> list:
        """Replay a delta window over decoded base metadata: append
        the (name, version, size) entries past the base head and adopt
        the delta's applied cursors. Ignores an absent/corrupt delta
        or one pinned to a different base (stale pairing). Returns the
        effective shard_applied list."""
        delta = OSDDaemon._decode_meta_delta(delta_blob) \
            if delta_blob else None
        if delta is None:
            return applied
        _, base_head, d_applied, entries = delta
        if base_head != log.head:
            return applied       # delta extends a different base
        for name, ver, size in entries:
            if ver <= log.head:
                continue         # defensive: never rewind
            log.append_entry(ver, name)
            versions[name] = ver
            sizes[name] = size
        if len(d_applied) == len(applied):
            applied = [max(a, b) for a, b in zip(applied, d_applied)]
        return applied

    def _restore_backend(self, ps: int, acting: list[int]):
        """Primary takeover: rebuild the PG from persisted metadata.
        The backend is restored with the acting set the metadata was
        recorded against — _reconcile then sees old != new and runs
        the recovery that re-creates the changed slots (the GetLog/
        GetMissing outcome)."""
        blob, local_blob, quorum_ok = self._load_meta(ps, acting)
        if not quorum_ok:
            # we could not hear a majority of the up acting members:
            # restoring now could crown a divergent local log — or
            # start a VIRGIN history whose first persist would beat
            # the unreachable peers' real data on epoch precedence.
            # Stay un-activated; the heartbeat reconcile retries
            # until the gather reaches quorum.
            self.c.log(f"{self.name}: pg 1.{ps} restore deferred "
                       f"(info gather below quorum)")
            return None
        be = self._make_backend(ps, acting)
        be.restored_from_blob = blob is not None
        if blob is None:
            return be            # virgin PG: nothing written yet
        import json as _json
        base, delta_blob = blob
        d, v = self._meta_decoder(base)
        if v >= 3:
            d.u64()              # persist epoch (used by _meta_rank)
        be.object_sizes = d.mapping(Decoder.string, Decoder.u64)
        be.object_versions = d.mapping(Decoder.string, Decoder.u64)
        be.pg_log = PGLog.decode(d.blob())
        applied = d.list(Decoder.u64)
        meta_acting = d.list(Decoder.i32)
        if v >= 2:
            self.snapsets[ps] = d.mapping(
                Decoder.string,
                lambda dd: dd.list(lambda e2: (e2.u64(), e2.u64())))
            self.births[ps] = d.mapping(Decoder.string, Decoder.u64)
            self.obj_kv[ps] = {
                k: _json.loads(b) for k, b in d.mapping(
                    Decoder.string, Decoder.blob).items()}
        d.finish()
        # roll the delta window forward over the base (the entries
        # persisted since the last full blob — see _meta_extra)
        applied = self._apply_meta_delta(
            delta_blob, be.object_sizes, be.object_versions,
            be.pg_log, applied)
        # adopt the RECORDED acting so the reconcile pass recovers any
        # slot whose OSD has since changed (collections for the new
        # set already exist — _make_backend created them above)
        be.acting = list(meta_acting)
        be.shard_applied = list(applied)
        # divergent-log rewind (ref: PGLog::merge_log): this daemon's
        # own persisted log may hold entries the authoritative blob
        # does not — writes from a dead interval that never committed.
        # Those objects must be rolled back to authoritative state,
        # never served from the tainted local copy.
        if local_blob is not None and local_blob != blob:
            try:
                lbase, ldelta = local_blob
                ld, lv = self._meta_decoder(lbase)
                if lv >= 3:
                    ld.u64()
                lsizes = ld.mapping(Decoder.string, Decoder.u64)
                lvers = ld.mapping(Decoder.string, Decoder.u64)
                local_log = PGLog.decode(ld.blob())
                self._apply_meta_delta(ldelta, lsizes, lvers,
                                       local_log, [])
            except Exception:    # noqa: BLE001 — corrupt local blob:
                local_log = None  # nothing credible to rewind
            if local_log is not None:
                div = divergent_names(local_log, be.pg_log)
                if div and not share_history(local_log, be.pg_log):
                    # no entry agreement at all: interval
                    # DISCONTINUITY, not a stale tail — removing the
                    # "divergent" objects could delete the only copies
                    # (full-acting-set outage then virgin restart).
                    # QUARANTINE the bytes into a side collection:
                    # out of the data path AND out of repair's stray
                    # sweep (which would otherwise delete them on the
                    # next routine `pg repair`).
                    self._quarantine_divergent(ps, be, div)
                elif div:
                    try:
                        self._rewind_divergent(ps, be, div)
                    except Exception as e:  # noqa: BLE001 — a failed
                        # rewind must not block the takeover; retry on
                        # the next reconcile
                        self.c.log(f"{self.name}: pg 1.{ps} rewind "
                                   f"errored ({e}); queued for retry")
                        self._rewind_pending.setdefault(
                            ps, set()).update(div)
        # stripe-journal replay (r16): a primary crash mid-RMW leaves
        # intents on the participating shards — settle them (forward
        # or back, never torn) BEFORE this backend serves a single op.
        # Map-known-down and suspected OSDs are skipped up front (a
        # sync scan frame to a dead peer would stall a whole
        # op_timeout); shards that fail mid-scan are skipped the same
        # way, and the next reconcile's restore retries them.
        try:
            down = {o for o in range(len(self.osdmap.osd_up))
                    if not self.osdmap.osd_up[o]}
            rep = be.stripe_journal_replay(
                dead_osds=down | set(self.suspect))
            if rep["entries"]:
                self.c.log(f"{self.name}: pg 1.{ps} stripe-journal "
                           f"replay: {rep}")
        except (ConnectionError, OSError, KeyError) as e:
            self.c.log(f"{self.name}: pg 1.{ps} stripe-journal "
                       f"replay deferred: {e}")
        return be

    def _quarantine_divergent(self, ps: int, be,
                              names: list[str]) -> None:
        """Move dead-interval objects that share NO history with the
        authoritative log into `<pgid>.quarantine` on this daemon's
        own store — preserved for the operator (ceph_objectstore_tool
        export/inspect), invisible to reads, scrub, and the repair
        stray sweep."""
        from .pgbackend import HINFO_KEY
        pgid = f"1.{ps}"
        qcid = f"{pgid}.quarantine"
        moved = 0
        for name in sorted(names):
            for s in range(be.n):
                cid = shard_cid(be.pg, s)
                if not self.store.exists(cid, name):
                    continue
                data = self.store.read(cid, name)
                qoid = f"{name}@s{s}"
                t = (Transaction().create_collection(qcid)
                     .write(qcid, qoid, 0, data)
                     # truncate: a prior incident's longer quarantined
                     # copy must not leave stale tail bytes under the
                     # same oid
                     .truncate(qcid, qoid, len(data))
                     .remove(cid, name))
                try:
                    # preserve the integrity metadata with the bytes:
                    # the operator verifies the export against hinfo
                    hb = self.store.getattr(cid, name, HINFO_KEY)
                    t.setattr(qcid, qoid, HINFO_KEY, hb)
                except KeyError:
                    pass   # a raw dead-interval write may lack hinfo
                self.store.queue_transaction(t)
                moved += 1
        self.c.log(f"{self.name}: pg {pgid} local history shares no "
                   f"entries with the authoritative log; quarantined "
                   f"{moved} shard object(s) to {qcid} (operator: "
                   f"ceph_objectstore_tool export/inspect)")

    def _rewind_divergent(self, ps: int, be, names: list[str]) -> None:
        """Roll back writes only this daemon's dead interval logged
        (ref: PGLog merge_log divergent handling + missing-set repair).
        A name the authoritative history knows is ROLLED FORWARD from
        the authoritative copies (rewriting every shard converges the
        tainted one); a name it never committed is REMOVED from this
        daemon's own store — serving or resurrecting it would
        acknowledge a write the cluster never accepted. Leftovers are
        scanned across ALL of the PG's local collections: the
        takeover interval re-slotted the PG, so the divergent bytes
        sit in whatever slot this daemon held in the DEAD interval,
        not necessarily one the authoritative acting still assigns
        to it."""
        pending = self._rewind_pending.setdefault(ps, set())
        for name in sorted(names):
            if name in be.object_sizes:
                try:
                    data = be.read_objects(
                        [name], dead_osds={self.osd_id})[name]
                    be.write_objects(
                        {name: bytes(np.asarray(data, np.uint8)
                                     .tobytes())},
                        dead_osds=set(self.suspect))
                    pending.discard(name)
                    self.c.log(f"{self.name}: pg 1.{ps} rewound "
                               f"divergent {name!r} from "
                               f"authoritative copies")
                except Exception as e:   # noqa: BLE001 — retried on
                    pending.add(name)    # the next reconcile
                    self.c.log(f"{self.name}: pg 1.{ps} divergent "
                               f"{name!r} rewind deferred: {e}")
                continue
            for s in range(be.n):
                cid = shard_cid(be.pg, s)
                if self.store.exists(cid, name):
                    self.store.queue_transaction(
                        Transaction().remove(cid, name))
            pending.discard(name)
            self.c.log(f"{self.name}: pg 1.{ps} discarded divergent "
                       f"uncommitted {name!r}")
        if not pending:
            self._rewind_pending.pop(ps, None)

    def _on_map(self, peer: str, msg: MOSDMapMsg) -> None:
        with self._lock:
            if self.osdmap is not None \
                    and msg.epoch <= self.osdmap.epoch:
                return
            self._adopt_map_locked(OSDMap.decode(msg.map_bytes))

    def _on_inc_map(self, peer: str, msg: MOSDIncMapMsg) -> None:
        """Delta fan-out arm of the map subscription: chain the
        incremental when it extends our epoch exactly; on any gap
        (fresh boot, missed broadcast, partition heal) ask the sender
        for a full map instead of guessing. The apply mutates a
        shallow CLONE and swaps — readers holding self.osdmap never
        see a half-applied epoch."""
        with self._lock:
            cur = self.osdmap
            if cur is not None and msg.epoch <= cur.epoch:
                return
            if cur is not None and msg.epoch == cur.epoch + 1:
                inc = Incremental.decode(msg.map_bytes)
                if inc.base_epoch == cur.epoch:
                    self.perf.inc("map_incs_applied")
                    self._adopt_map_locked(
                        inc.apply(cur.shallow_clone()))
                    return
            self.perf.inc("map_full_requests")
        try:
            self.msgr.send(peer, MOSDMapRequest(
                self.osdmap.epoch if self.osdmap is not None else 0))
        except (KeyError, OSError, ConnectionError):
            pass

    def _adopt_map_locked(self, newmap: OSDMap) -> None:
        """Land a newer map (full decode or chained incremental) —
        caller holds self._lock and has checked epoch monotonicity."""
        self.osdmap = newmap
        # an OSD the map marks UP again is no longer suspect and
        # may be REPORTED again on its next real failure (else a
        # revived OSD's second death would never reach the mon)
        now = time.monotonic()
        for osd in self.c.osd_ids():
            if osd != self.osd_id and self.osdmap.osd_up[osd]:
                if osd in self._reported or osd in self.suspect:
                    self._last_pong[osd] = now
                self._reported.discard(osd)
                self.suspect.discard(osd)
        # r17: fold the committed liveness into the repair policy's
        # DownClocks BEFORE reconciling — a down mark starts a
        # deferral window, a revive cancels the parked work and queues
        # the cursor re-check the reconcile below will consume. Only
        # an ADMIN out (`osd out`, sticky) confirms instantly: the
        # harness's automatic down+out rides EVERY down mark and is
        # exactly the transient evidence the delay exists to absorb.
        self.repair_policy.observe_map(
            self.osdmap.osd_up,
            out_osds=sorted(getattr(self.osdmap, "osd_admin_out",
                                    None) or ()),
            now=now, suspect=self.suspect)
        self._apply_central_config()
        self._reconcile()
        self.perf.set("osdmap_epoch", self.osdmap.epoch)
        self.perf.set("numpg", len(self.backends))

    def _apply_central_config(self) -> None:
        """Land the committed map's config KV at this daemon's "mon"
        config layer (ConfigMonitor -> md_config_t flow): sets fire
        observers only on resolved-value change, removed keys fall
        back to the file/default layers, unknown keys are logged and
        skipped (a newer cluster may ship options this daemon doesn't
        declare — the reference warns and continues the same way)."""
        kv = self.osdmap.config_kv
        for key, value in kv.items():
            if self._cfg_applied.get(key) == value:
                continue
            try:
                self.config.set(key, value, level="mon")
            except (KeyError, ValueError) as e:
                self.c.log(f"{self.name}: central config "
                           f"{key}={value!r} ignored: {e}")
            self._cfg_applied[key] = value
        for key in [k for k in self._cfg_applied if k not in kv]:
            try:
                self.config.rm(key, level="mon")
            except KeyError:
                pass
            del self._cfg_applied[key]
        # QoS knobs may have moved: re-resolve the mClock profile table
        # (live, no restart — the osd_mclock config-change path)
        self._refresh_mclock_profiles()

    def _reconcile(self) -> None:
        """Map changed: adopt/recover the PGs this daemon primaries
        (the PeeringState Get* exchange outcome, driven from the
        authoritative persisted metadata). Recovery is PLANNED here but
        EXECUTED by the mClock worker: every primaried PG's plan joins
        ONE cross-PG round whose fused batches interleave with client
        ops (the pre-r10 tree ran one blocking recover_shards per PG
        inside this loop, holding the daemon lock for the whole
        rebuild)."""
        new_plans: list[tuple[int, object, set[int]]] = []
        for ps in range(self.c.pg_num):
            # per-PG lock INSIDE the daemon lock (one global order):
            # client ops of this PG are excluded while its backend/
            # meta move; other PGs' ops keep flowing
            with self._pg_lock(ps):
                self._reconcile_pg(ps, new_plans)
        if new_plans:
            # r17 risk order: most exposed stripes first (fewest
            # surviving redundancy shards), r14 helper cost second,
            # PG id last — the runner drains batches in plan order,
            # so this IS the exposure schedule. 'pgid' keeps the
            # pre-r17 order selectable (the exposure A/B the bench
            # measures) but still counts the inversions it ships.
            from .repairpolicy import order_plans
            new_plans = order_plans(
                new_plans, self._plan_redundancy,
                mode=str(self.config["osd_repair_queue_order"]),
                counter=self.repair_policy._count)
            now_m = time.monotonic()
            for ps, plan, _dead in new_plans:
                self.repair_policy.note_exposure(
                    ps, self._plan_redundancy(ps, plan) <= 1,
                    now=now_m)
            rnd = _RecoveryRound(self, new_plans)
            for ps, _plan, _dead in new_plans:
                self._recovering[ps] = rnd
            self._sched_enqueue("background_recovery", rnd,
                                rnd.next_cost(), shard=rnd.shard())
        self._note_repair_gauges()

    def _plan_redundancy(self, ps: int, plan) -> int:
        """Surviving redundancy of one planned rebuild: failures the
        PG can still absorb while the plan is queued (EC: m - lost;
        replicated: spare copies). The risk key's first component."""
        be = self.backends.get(ps)
        if be is None:
            return 0
        return (be.n - be.min_live) - len(getattr(plan, "lost", ()))

    def _note_repair_gauges(self) -> None:
        self.perf.set("repair_parked_pgs",
                      len(self.repair_policy.parked))
        self.perf.set("repair_exposed_pgs",
                      self.repair_policy.exposed_pgs())

    def _reconcile_pg(self, ps: int, new_plans: list) -> None:
        """One PG's slice of _reconcile. Caller holds self._lock and
        the PG lock."""
        acting = self._acting(ps)
        if not acting or acting[0] != self.osd_id:
            if self.backends.pop(ps, None) is not None:
                # not ours (anymore): the new primary restores
                # snap/cls state from the PG metadata
                self.snapsets.pop(ps, None)
                self.births.pop(ps, None)
                self.obj_kv.pop(ps, None)
                self.scrub_reports.pop(ps, None)
                self._last_scrub.pop(ps, None)
                self._last_deep.pop(ps, None)
                self._meta_delta.pop(ps, None)
            self._interval_start.pop(ps, None)
            self._last_acting.pop(ps, None)
            # not our PG: drop any repair-policy bookkeeping for it
            # (the new primary re-derives its own)
            self.repair_policy.note_planned(ps)
            self.repair_policy.take_recheck(ps)
            self.repair_policy.note_exposure(ps, False,
                                             now=time.monotonic())
            return
        # interval detection: any acting change starts a NEW
        # INTERVAL whose primary must re-prove freshness — its
        # up_thru must reach the interval's start epoch before the
        # PG restores/recovers/serves (WaitUpThru; ref:
        # PeeringState::adjust_need_up_thru)
        if self._last_acting.get(ps) != acting:
            self._last_acting[ps] = list(acting)
            self._interval_start[ps] = self.osdmap.epoch
        need_ut = self._interval_start.get(ps, 0)
        if int(self.osdmap.osd_up_thru[self.osd_id]) < need_ut:
            self._request_up_thru(need_ut)
            return
        be = self.backends.get(ps)
        if be is None:
            now_m = time.monotonic()
            if now_m < self._restore_backoff.get(ps, 0.0):
                return          # recent below-quorum gather:
            #                     don't re-pay its RPC timeouts
            #                     on every map/heartbeat tick
            try:
                be = self._restore_backend(ps, acting)
            except (ConnectionError, OSError, KeyError) as e:
                # transient transport/auth trouble mid-restore
                # (cold tickets fail fast, a helper died): defer
                # with the same backoff as a below-quorum gather
                self.c.log(f"{self.name}: pg 1.{ps} restore "
                           f"deferred ({e})")
                self._restore_backoff[ps] = now_m + 2.0
                return
            if be is None:      # info gather below quorum:
                self._restore_backoff[ps] = now_m + 2.0
                return          # retried by the heartbeat tick
            self._restore_backoff.pop(ps, None)
            self.backends[ps] = be
            if getattr(be, "restored_from_blob", False):
                # ACTIVATION (the last_epoch_started role): stamp
                # this interval's epoch onto the acting members
                # BEFORE recovery starts or I/O is served — a
                # member of the old interval rejoining mid-
                # takeover must find the new interval's claim on
                # the quorum, or its longer dead-interval log
                # would win the info gather and resurrect
                # uncommitted writes (ref: PeeringState::activate)
                try:
                    self._persist_meta(ps)
                except Exception as e:  # noqa: BLE001
                    self.c.log(f"{self.name}: pg 1.{ps} "
                               f"activation persist failed: {e}")
        elif self._rewind_pending.get(ps):
            # a deferred divergent rewind retries on every map
            # change until its helpers are reachable
            self._rewind_divergent(
                ps, be, sorted(self._rewind_pending[ps]))
        if be.acting == acting:
            self._snap_trim(ps, be)   # snaps may have left the map
            # r17 lazy repair, the payoff branch: a parked OSD revived
            # inside its window and the map folded back to the old
            # acting — cancel cost is a CURSOR re-check, not a rebuild
            recheck = self.repair_policy.take_recheck(ps)
            if recheck:
                self._revive_recheck(ps, be, recheck, new_plans)
            rnd = self._recovering.get(ps)
            if rnd is not None and getattr(rnd, "failed", False):
                # a round died mid-way (helper lost, push refused):
                # re-plan THIS pg in full — helpers re-validate
                # against the current map, already-landed objects
                # re-verify cheaply through the fused pipeline
                n_osds = len(self.osdmap.osd_up)
                exclude = {
                    s for s, o in enumerate(be.acting)
                    if s not in rnd.lost_of(ps)
                    and (not _valid_osd(o, n_osds)
                         or o in self.suspect
                         or not self.osdmap.osd_up[o])}
                try:
                    plan = be.plan_recovery(
                        rnd.lost_of(ps), helper_exclude=exclude,
                        helper_costs=self._helper_costs(be))
                    self._recovering[ps] = None   # round pending
                    new_plans.append((ps, plan, set()))
                except (ValueError, ConnectionError, KeyError) as e:
                    self.c.log(f"{self.name}: pg 1.{ps} recovery "
                               f"retry deferred: {e}")
        if be.acting != acting:
            # a changed slot whose old OSD is still up is a MOVE
            # (CRUSH re-slotted a live member: copy the shard
            # bytes); only a dead old OSD is a LOSS (decode-rebuild
            # from helpers). Conflating them would overrun m.
            lost, moves = [], []
            n_osds = len(self.osdmap.osd_up)
            for s, (o, n) in enumerate(zip(be.acting, acting)):
                if o == n:
                    continue
                if not _valid_osd(n, n_osds):
                    # CRUSH couldn't fill this slot in the current
                    # (degraded) epoch — acting carries the
                    # ITEM_NONE sentinel. Addressing "osd.<2^31>"
                    # would KeyError mid-dispatch; leave the slot
                    # where it is and retry on a better map.
                    continue
                if _valid_osd(o, n_osds) \
                        and self.osdmap.osd_up[o] \
                        and o not in self.suspect:
                    moves.append((s, o, n))
                else:
                    # dead old holder — or a hole: a slot born
                    # unfillable has no old bytes anywhere and
                    # must decode-rebuild, not copy
                    lost.append(s)
            # r17 lazy repair: while EVERY dead old holder is inside
            # its osd_repair_delay window (down_deferred) and no
            # override fires (m-1 exposure, stripe budget, out mark),
            # PARK this PG's rebuild — plan nothing, move nothing.
            # Holes (slots born unfillable) never defer: there is no
            # OSD to wait for. Deferral re-evaluates on every map fold
            # and heartbeat reconcile, so the window expiring, a
            # second failure, or a revive all resolve it within a beat.
            if lost:
                dead_hold = {be.acting[s] for s in lost
                             if _valid_osd(be.acting[s], n_osds)}
                holes = len(dead_hold) < len(lost)
                fresh_park = ps not in self.repair_policy.parked
                if (not holes
                        and self.repair_policy.should_defer(
                            ps, dead_hold, len(lost),
                            be.n - be.min_live,
                            max(1, len(be.object_sizes)))):
                    if fresh_park:
                        self.c.log(
                            f"{self.name}: pg 1.{ps} rebuild parked "
                            f"(lazy repair, dead={sorted(dead_hold)}, "
                            f"delay="
                            f"{self.config['osd_repair_delay']}s)")
                    return
            # r21 capacity gate: a rebuild writes a full shard into
            # every replacement target — parking while a target sits
            # at/over backfillfull is what keeps recovery from driving
            # a nearly-full OSD through the FULL cliff. Re-evaluated
            # every reconcile (flag clears / CRUSH repoints resolve it
            # within a beat); an m-1 stripe overrides — losing the
            # stripe is strictly worse than the space risk.
            if lost:
                blocked = sorted(
                    acting[s] for s in lost
                    if _valid_osd(acting[s], n_osds)
                    and self.osdmap.full_state_of(acting[s])
                    >= FULL_BACKFILLFULL)
                if blocked:
                    urgent = (be.n - be.min_live) - len(lost) <= 1
                    if not urgent:
                        if ps not in self._bff_parked:
                            self._bff_parked.add(ps)
                            self.repair_policy._count(
                                "repair_backfillfull_parked")
                            self.c.log(
                                f"{self.name}: pg 1.{ps} rebuild "
                                f"parked (targets {blocked} "
                                f"backfillfull)")
                        return
                    self.c.log(f"{self.name}: pg 1.{ps} rebuild into "
                               f"backfillfull {blocked} (m-1 urgent "
                               f"override)")
                self._bff_parked.discard(ps)
            # an acting change subsumes any queued revive re-check
            # (the move/loss handling below re-derives freshness)
            self.repair_policy.take_recheck(ps)
            try:
                for s, o, n in moves:
                    self._move_shard(be, s, o, n)
                if lost:
                    self.repair_policy.note_planned(ps)
                    repl = {s: acting[s] for s in lost}
                    dead = {be.acting[s] for s in lost}
                    exclude = {
                        s for s, o in enumerate(be.acting)
                        if s not in lost
                        and (not _valid_osd(o, n_osds)
                             or o in self.suspect
                             or not self.osdmap.osd_up[o])}
                    # plan now (validates helpers, repoints the
                    # lost slots so new client writes reach the
                    # rebuilding store directly); the mClock
                    # worker executes the batches. The recovering
                    # marker goes up IN THE SAME locked breath as
                    # the acting mutation — wait_for_clean polls
                    # unlocked and must never see a repointed
                    # acting without the in-flight marker.
                    # Replicated pools have no fused decode plan:
                    # their push-based recover_shards runs inline
                    # (the pre-r10 path; copies, not decodes).
                    if hasattr(be, "plan_recovery"):
                        plan = be.plan_recovery(
                            lost, replacement_osds=repl,
                            helper_exclude=exclude,
                            helper_costs=self._helper_costs(be))
                        self._recovering[ps] = None  # round pending
                        new_plans.append((ps, plan, dead))
                    else:
                        be.recover_shards(lost,
                                          replacement_osds=repl,
                                          helper_exclude=exclude)
                        self.suspect -= dead
                        self.perf.inc("recovery_rounds")
                self._persist_meta(ps)
            except (ValueError, ConnectionError, KeyError) as e:
                self.c.log(f"{self.name}: pg 1.{ps} recovery "
                           f"deferred: {e}")

    def _revive_recheck(self, ps: int, be, revived: set[int],
                        new_plans: list) -> None:
        """Cancel cost of lazy repair: for every slot whose OSD came
        back inside its deferral window, walk the PG log from the
        slot's applied cursor (the cursor/version re-check). A quiet
        window proves the shard current — ZERO bytes move, counted in
        repair_cancel_noop. Writes that landed inside the window
        replay through the existing names= delta-recovery path (only
        the missed objects, not a rebuild). A log trimmed past the
        cursor cannot prove either way and falls back to a full plan.
        Caller holds self._lock and the PG lock."""
        slots = [s for s, o in enumerate(be.acting) if o in revived]
        if not slots:
            self.repair_policy.note_recheck(0)
            return
        names: set[str] | None = set()
        for s in slots:
            missing = be.pg_log.missing_since(be.shard_applied[s])
            if missing is None:
                names = None            # log trimmed: full rebuild
                break
            names.update(missing)
        if names is not None and not names:
            self.repair_policy.note_recheck(0)
            self.c.log(f"{self.name}: pg 1.{ps} parked rebuild "
                       f"cancelled by revive (cursor re-check clean, "
                       f"0 bytes)")
            return
        n_catchup = len(names) if names is not None \
            else len(be.object_sizes)
        self.repair_policy.note_recheck(n_catchup)
        try:
            if hasattr(be, "plan_recovery"):
                plan = be.plan_recovery(
                    slots,
                    names=sorted(names) if names is not None else None,
                    helper_costs=self._helper_costs(be))
                self._recovering[ps] = None      # round pending
                new_plans.append((ps, plan, set()))
            else:
                be.recover_shards(
                    slots,
                    names=sorted(names) if names is not None else None)
                self.perf.inc("recovery_rounds")
            self.c.log(f"{self.name}: pg 1.{ps} revive catch-up: "
                       f"{n_catchup} object(s) missed inside the "
                       f"window")
        except (ValueError, ConnectionError, KeyError) as e:
            self.c.log(f"{self.name}: pg 1.{ps} revive catch-up "
                       f"deferred: {e}")

    def _request_up_thru(self, want: int) -> None:
        """Ask every monitor to record our up_thru through `want` (the
        MOSDAlive flow): broadcast so whoever leads proposes; the
        committed map comes back via the normal subscription and the
        next reconcile finds the interval activatable. Re-sent on
        every reconcile while the window is open — a request consumed
        by a monitor that lost leadership must not strand the PG."""
        for mon_name in self.c.mon_names():
            try:
                self.msgr.send(mon_name, MOSDAlive(self.osd_id, want))
            except (KeyError, OSError, ConnectionError):
                pass

    def _move_shard(self, be, slot: int, old_osd: int,
                    new_osd: int) -> None:
        """Backfill-by-copy for a re-slotted LIVE member: pull the
        shard's bytes from the old holder, push to the new one — all
        as store-op frames (the backfill push role)."""
        from .pgbackend import HINFO_KEY
        cid = shard_cid(be.pg, slot)
        src = be.cluster.osd(old_osd)
        dst = be.cluster.osd(new_osd)
        t = Transaction().create_collection(cid)
        moved_objs = moved_bytes = 0
        for name in be.list_pg_objects():
            if not src.exists(cid, name):
                continue
            data = np.asarray(src.read(cid, name), np.uint8)
            t.write(cid, name, 0, data).truncate(cid, name, len(data))
            moved_objs += 1
            moved_bytes += len(data)
            try:
                t.setattr(cid, name, HINFO_KEY,
                          src.getattr(cid, name, HINFO_KEY))
            except KeyError:
                pass
        dst.queue_transaction(t)
        # repair-traffic accounting (r17): backfill copies are repair
        # bytes too — the storm bench sums them with recovered_bytes
        self.perf.inc_many((("move_objects", moved_objs),
                            ("move_bytes", moved_bytes)))
        be.acting[slot] = new_osd
        self.c.log(f"{self.name}: pg {be.pg} slot {slot} moved "
                   f"osd.{old_osd} -> osd.{new_osd}")

    # -- client ops ----------------------------------------------------------

    def _init_observability(self) -> None:
        """Fresh OpTracker + PerfCounters — called at boot AND on
        revive (in-RAM observability dies with the process, like a
        real restart); ONE list of counter keys so the two paths
        cannot drift. The OpTracker resolves its thresholds through
        this daemon's layered config (osd_op_complaint_time /
        osd_op_history_*), so a committed `config set` retunes it
        live."""
        from ..utils.flight_recorder import FlightRecorder
        from ..utils.op_tracker import OpTracker
        from ..utils.perf_counters import PerfCountersBuilder
        from .ecbackend import ec_perf_counters
        self.op_tracker = OpTracker(config=self.config)
        # per-daemon flight recorder (r15): bounded ring of finished
        # trace spans, in-RAM like the rest of the observability plane
        # (dies with the process; rebuilt here on revive). Dumped via
        # `trace dump`, drained into MgrReports for the mon assembler.
        self.flight = FlightRecorder(self.name, config=self.config)
        b = PerfCountersBuilder(f"osd.{self.osd_id}")
        for key in ("op", "op_r", "op_w", "op_in_bytes",
                    "op_out_bytes"):
            b.add_u64_counter(key)
        (b.add_u64_counter("subop", "store sub-ops served")
         .add_u64_counter("subop_in_bytes", "store sub-op bytes in")
         .add_u64_counter("subop_out_bytes", "store sub-op bytes out")
         .add_u64_counter("recovery_rounds",
                          "reconcile-driven recovery passes")
         .add_u64_counter("cephx_refresh_kicked",
                          "background ticket refreshes started")
         .add_u64_counter("cephx_refresh_coalesced",
                          "refresh requests folded into an already "
                          "running single-flight fetch")
         .add_u64_counter("authorize_deferred",
                          "dispatch-path authorizes failed fast on a "
                          "cold ticket cache")
         .add_u64_counter("mgr_reports_tx", "MgrReports shipped")
         .add_u64_counter("op_degraded_read",
                          "objects served through the degraded-read "
                          "fast path (any-k decode, peering bypassed)")
         .add_u64_counter("degraded_view_builds",
                          "read-only degraded views built (meta "
                          "gather + decode, non-primary serves)")
         .add_time_avg("degraded_read_time",
                       "degraded-read service time (gather + any-k "
                       "decode)")
         .add_u64_counter("op_shard_grants",
                          "ops granted by shard workers (all shards; "
                          "per-shard split in dump_op_shards)")
         .add_u64("op_shard_depth",
                  "ops queued across all op shards right now")
         .add_u64("op_shard_imbalance",
                  "grant spread across shards (max-min served — the "
                  "PG-hash skew signal)")
         .add_u64_counter("move_objects",
                          "objects copied by backfill-by-copy shard "
                          "moves (a re-slotted LIVE member)")
         .add_u64_counter("move_bytes",
                          "bytes copied by backfill-by-copy shard "
                          "moves (with ec.recovered_bytes and "
                          "ec.recover_wire_bytes: the repair-traffic "
                          "total the r17 policy plane prices)")
         .add_u64("repair_parked_pgs",
                  "PGs whose rebuild is parked behind "
                  "osd_repair_delay right now (lazy repair)")
         .add_u64("repair_exposed_pgs",
                  "PGs at m-1 surviving redundancy right now (the "
                  "PG_EXPOSED health source; risk ordering drains "
                  "these first)")
         .add_u64("numpg", "PGs this daemon primaries")
         .add_u64("osdmap_epoch", "newest folded map epoch")
         .add_u64_counter("map_incs_applied",
                          "incremental OSDMaps chained onto the "
                          "current epoch (delta fan-out path)")
         .add_u64_counter("map_full_requests",
                          "full-map requests sent after an "
                          "unchainable incremental (gap/fresh boot)")
         .add_time_avg("op_latency",
                       "client op wall time (tracker enter to reply "
                       "built)", hist=True)
         .add_time_avg("op_r_latency",
                       "read-kind client op wall time (the "
                       "client_read SLO feed)", hist=True)
         .add_time_avg("op_w_latency",
                       "write-kind client op wall time (the "
                       "client_write SLO feed)", hist=True)
         .add_time_avg("subop_latency", "store sub-op service time",
                       hist=True)
         .add_u64("trace_dropped_unshipped",
                  "flight-ring spans evicted before an MgrReport "
                  "shipped them (persistent growth -> "
                  "TRACE_RING_OVERFLOW)")
         .add_u64_counter("retro_subop_published",
                          "retro.subop spans published from the "
                          "sub-op retro ring on a peer's slow-op "
                          "fan-out")
         .add_u64_counter("writes_rejected_full",
                          "mutating client ops bounced for capacity "
                          "(failsafe hard-stop or map FULL flag) — "
                          "each bounce parks the client, it never "
                          "surfaces as an op_error")
         # r22 network observability: the DECLARED aggregate over all
         # peer links (per-link detail is dynamic-keyed, so it rides
         # the MgrReport "network" side-field, never counter names)
         .add_time_avg("hb_ping_rtt",
                       "heartbeat ping round trip, all peer links "
                       "folded (per-link lhists ride the report's "
                       "network block into the mon NetworkAggregator)",
                       hist=True)
         .add_u64_counter("net_helper_penalties",
                          "helper-cost slots where the hb-RTT link "
                          "feed (r22) raised the cost above the "
                          "store/client view — the planner saw the "
                          "wire, not just the service time"))
        # r17 repair-policy counters: declared from the policy
        # module's ONE list so the daemon schema and the policy's own
        # counter dict cannot drift (the r9 declared-names rule)
        from .repairpolicy import POLICY_COUNTERS
        for key in POLICY_COUNTERS:
            b.add_u64_counter(key, "repair policy plane (r17) — see "
                                   "osd/repairpolicy.py")
        self.perf = b.create_perf_counters()
        # ONE "ec" logger shared by every PG backend this daemon
        # hosts (per-PG loggers would explode the metric space)
        self.ec_perf = ec_perf_counters()
        # MgrReport delta stream state (see mgr/reports.py)
        self._mgr_seq = 0
        self._mgr_last_perf: dict | None = None
        self._mgr_last_sent = 0.0
        # r18 telemetry plane: per-interval counter/histogram deltas,
        # bounded, live-tuned (mgr_history_interval/_len); entries
        # drain into MgrReports, `perf history` answers locally
        from ..utils.perf_counters import MetricsHistory
        self.metrics_history = MetricsHistory(self.perf_dump_all,
                                              config=self.config)
        # r19 continuous CPU profiling: a dedicated sampler thread
        # folds every thread's stack into span-tagged collapsed
        # stacks at daemon_profile_hz (live; 0 = off). In-RAM like
        # the rest of the plane — a revive gets a fresh profile.
        from ..utils.profiler import SamplingProfiler
        self.profiler = SamplingProfiler(self.name,
                                         config=self.config).start()
        # r22 network observability: per-(peer, channel) RTT fold —
        # in-RAM like the rest of the plane (a revive measures fresh;
        # _init_observability runs on both paths). Pong fast dispatch
        # and store RPC completions feed it; the heartbeat ships it.
        from ..mgr.netobs import LinkTracker
        self.link_tracker = LinkTracker(perf=self.perf)
        # peers currently flagged slow-link (hysteresis for the r17
        # DownClock evidence: flag at threshold, clear at half)
        self._slow_links: set[int] = set()
        # r18 sub-op retro ring (the r15 replica gap): completed store
        # sub-ops remembered by carried trace id so a primary's slow-op
        # retro assembly can pull this hop's timing after the fact
        # (retro_publish). In-RAM, dies with the process like the
        # flight ring.
        self._subop_ring: list[dict] = []
        self._subop_ring_lock = threading.Lock()

    # -- perf dump assembly (admin socket + wire admin op + MgrReport) -------

    def perf_dump_all(self) -> dict:
        """Every logger this daemon owns, keyed the way `ceph daemon
        osd.N perf dump` shows them. Assembled ONLY from declared
        PerfCounters dumps — the counter-name smoke test depends on
        that."""
        out = {self.perf.name: self.perf.dump(),
               "msgr": self.msgr.perf.dump(),
               "rpc": self.rpc.perf.dump(),
               "ec": self.ec_perf.dump()}
        if self._cauth is not None:
            out["cephx"] = self._cauth.perf.dump()
        kvp = getattr(self.store, "kv_perf", None)
        if kvp is not None:
            out["tindb"] = kvp.dump()
        return out

    def perf_schema_all(self) -> dict:
        out = {self.perf.name: self.perf.schema(),
               "msgr": self.msgr.perf.schema(),
               "rpc": self.rpc.perf.schema(),
               "ec": self.ec_perf.schema()}
        if self._cauth is not None:
            out["cephx"] = self._cauth.perf.schema()
        kvp = getattr(self.store, "kv_perf", None)
        if kvp is not None:
            out["tindb"] = kvp.schema()
        return out

    def perf_reset_all(self) -> None:
        self.perf.reset()
        self.msgr.perf.reset()
        self.rpc.perf.reset()
        self.ec_perf.reset()
        if self._cauth is not None:
            self._cauth.perf.reset()
        kvp = getattr(self.store, "kv_perf", None)
        if kvp is not None:
            kvp.reset()
        # the delta stream re-bases: a reset between two deltas would
        # otherwise ship huge negative deltas the aggregator folds
        # into nonsense
        self._mgr_last_perf = None

    _READ_KINDS = frozenset({"read", "readv", "read_degraded",
                             "snap_read", "admin"})

    _ADMIN_CMDS = ("perf dump", "perf reset", "perf schema",
                   "perf history",
                   "dump_historic_ops",
                   "dump_historic_ops_by_duration",
                   "dump_ops_in_flight", "slow_ops", "pg stat",
                   "pg clean",
                   "dump_mclock", "dump_op_shards", "dump_scrubs",
                   "dump_repair", "dump_osd_network",
                   "log dump",
                   "config show",
                   "config diff", "trace start", "trace stop",
                   "trace dump", "profile",
                   "status")

    def _pg_states(self) -> dict:
        """pg_state strings for the PGs this daemon primaries, through
        the GetInfo/GetLog/GetMissing classifier (the `ceph pg stat`
        slice a primary can answer; ref: PeeringState pg_state_t
        names). Caller holds self._lock."""
        from .peering import peer as _peer
        if self.osdmap is None:
            return {}
        alive = [bool(u) and o not in self.suspect
                 for o, u in enumerate(self.osdmap.osd_up)]
        my_ut = int(self.osdmap.osd_up_thru[self.osd_id])
        n_osds = len(alive)
        out = {}
        for ps, be in sorted(self.backends.items()):
            state = _peer(
                be, alive, compute_missing=False,
                interval_start=self._interval_start.get(ps, 0),
                up_thru=my_ut).state
            # r17: "+exposed" marks a PG at m-1 surviving redundancy
            # (one more failure loses data) — the PG_EXPOSED health
            # source, and what risk-ordered recovery drains first
            lost = sum(1 for o in be.acting
                       if not _valid_osd(o, n_osds) or not alive[o])
            if lost and (be.n - be.min_live) - lost <= 1:
                state += "+exposed"
            out[f"1.{ps}"] = state
        return out

    def _pool_bytes(self) -> dict:
        """Logical bytes per pool across the PGs this daemon primaries
        (the pg_stat_t num_bytes slice the autoscaler's capacity
        shares derive from; primaries-only so the cluster aggregate
        counts each object once, not size times). Caller holds
        self._lock. JSON-string pool keys — the report rides JSON."""
        total = sum(sum(be.object_sizes.values())
                    for be in self.backends.values())
        return {"1": int(total)} if self.backends else {}

    def _pool_objects(self) -> dict:
        """Object count per pool across primaried PGs (the
        pg_stat_t num_objects slice quota_max_objects is enforced
        against at the mon). Caller holds self._lock."""
        total = sum(len(be.object_sizes)
                    for be in self.backends.values())
        return {"1": int(total)} if self.backends else {}

    def _failsafe_gate(self, ps: int) -> None:
        """r21 osd_failsafe_full_ratio hard-stop (ref: OSDService::
        check_failsafe_full): statfs ratio at/over the failsafe bounces
        every mutating client op with the retryable park pattern. Local
        statfs only — deliberately map-independent, so it holds during
        the stale-map window before the mon ladder commits FULL."""
        try:
            st = self.store.statfs()
        except Exception:
            return
        total = int(st.get("total", 0))
        if not total:
            return                      # unbounded store: no ladder
        ratio = float(self.config["osd_failsafe_full_ratio"])
        if int(st.get("used", 0)) < ratio * total:
            return
        self.perf.inc("writes_rejected_full")
        raise RuntimeError(
            f"pg 1.{ps} osd.{self.osd_id} failsafe full "
            f"({st['used']}/{total} >= {ratio:.2f}, "
            f"epoch {self.osdmap.epoch})")

    def _admin_obj(self, cmd: str):
        """ONE dispatcher for both admin surfaces — the wire `admin`
        MOSDOp and the Unix admin socket (ref: src/common/
        admin_socket.cc registering OpTracker/PerfCounters/log
        commands) — so the two can't drift."""
        from ..utils.log import g_log
        if cmd == "perf dump":
            return self.perf_dump_all()
        if cmd == "perf schema":
            return self.perf_schema_all()
        if cmd == "perf reset":
            self.perf_reset_all()
            return {"success": True}
        if cmd.startswith("perf history"):
            # the r18 metric-history ring: per-interval deltas,
            # optional trailing-entry limit
            arg = cmd[len("perf history"):].strip()
            return self.metrics_history.dump(
                limit=int(arg) if arg else None)
        if cmd == "dump_historic_ops":
            return self.op_tracker.dump_historic_ops()
        if cmd == "dump_historic_ops_by_duration":
            return self.op_tracker.dump_historic_ops(by_duration=True)
        if cmd == "dump_ops_in_flight":
            return self.op_tracker.dump_ops_in_flight()
        if cmd == "slow_ops":
            return {"slow_ops": self.op_tracker.slow_ops(),
                    "complaint_time": self.op_tracker.complaint_time}
        if cmd == "log dump":
            # the gathered ring (more detail than was ever printed) —
            # during chaos runs the Thrasher's seed-stamped events are
            # in here, so this reconstructs the fault timeline
            return {"lines": g_log.dump_recent()}
        if cmd == "config show":
            return self.config.dump()
        if cmd == "config diff":
            return self.config.diff()
        if cmd.startswith("trace dump"):
            # the flight-recorder ring (r15): finished per-op trace
            # spans, optionally filtered to one trace id (hex)
            arg = cmd[len("trace dump"):].strip() or None
            return self.flight.dump(trace_id=arg)
        if cmd.startswith("profile"):
            # the r19 CPU sampler's cumulative span-tagged profile
            # (this daemon only; the cluster fold is the monitors'
            # `profile cpu`). `profile --collapsed` emits folded-
            # stack text instead of the raw category->stack counts.
            from ..utils.profiler import (category_split,
                                          collapsed_lines)
            dump = self.profiler.dump()
            if "--collapsed" in cmd:
                return {"name": self.name,
                        "collapsed": collapsed_lines(dump["stacks"])}
            dump["categories"] = category_split(dump["stacks"])
            return dump
        if cmd.startswith("trace start"):
            from ..utils.tracing import start_trace
            log_dir = cmd[len("trace start"):].strip() \
                or f"/tmp/{self.name}-trace"
            return {"started": start_trace(log_dir), "dir": log_dir}
        if cmd == "trace stop":
            from ..utils.tracing import stop_trace
            return {"stopped": stop_trace()}
        if cmd == "dump_mclock":
            # per-class occupancy + grants, tenant classes included,
            # MERGED across op shards (the pre-shard shape — tools
            # iterate class names at the top level)
            return self.sched_dump()
        if cmd == "dump_op_shards":
            # per-shard detail: the hash-spread view the merged
            # dump_mclock deliberately hides
            return self.shard_dump()
        if cmd == "dump_scrubs":
            with self._lock:   # heartbeat inserts concurrently
                return {"scrubs": {f"1.{ps}": r for ps, r in
                                   sorted(self.scrub_reports.items())}}
        if cmd == "dump_repair":
            # the r17 repair policy plane: DownClocks, parked
            # rebuilds, exposure + deferral counters, and the
            # per-failure-domain token buckets
            with self._lock:
                return {"policy": self.repair_policy.dump(),
                        "domains": self.domain_budgets.dump()}
        if cmd == "dump_osd_network":
            # the r22 link plane, THIS daemon's slice (ref: the
            # identically named OSD admin command): its own measured
            # links + flow ledger + any active injected degrades.
            # The cluster matrix is the monitors' dump_osd_network.
            return {
                "name": self.name,
                "threshold_ms": round(
                    self._slow_ping_threshold_s() * 1e3, 3),
                "links": self.link_tracker.dump(),
                "flow": self.msgr.flow_dump(),
                "slow_links": sorted(self._slow_links),
                "link_delays": self.msgr.link_delays(),
            }
        if cmd == "status":
            with self._lock:
                return {
                    "name": self.name,
                    "osdmap_epoch": self.osdmap.epoch
                    if self.osdmap is not None else 0,
                    "num_pgs": len(self.backends),
                    "suspect": sorted(self.suspect),
                    "store": type(self.store).__name__,
                }
        if cmd == "pg stat":
            with self._lock:
                return {"pgs": self._pg_states()}
        if cmd == "pg clean":
            # per-primaried-PG cleanliness, the wait_for_clean slice
            # one daemon can answer — the multi-process harness polls
            # this over the asok (it cannot reach into a child's RAM)
            with self._lock:
                if self.osdmap is None:
                    return {}
                out = {}
                for ps, be in self.backends.items():
                    acting = self._acting(ps)
                    out[f"1.{ps}"] = (bool(acting)
                                      and acting[0] == self.osd_id
                                      and be.acting == acting
                                      and ps not in self._recovering)
                return out
        raise ValueError(f"unknown admin command {cmd!r}; "
                         f"known: {list(self._ADMIN_CMDS)}")

    def _admin_cmd(self, cmd: str) -> bytes:
        """`ceph daemon osd.N <cmd>` over the wire."""
        import json as _json
        return _json.dumps(self._admin_obj(cmd), sort_keys=True,
                           default=str).encode()

    def _on_auth(self, peer: str, msg: MAuthOp) -> None:
        """Session establishment (ref: CephxAuthorizeHandler via
        ms_verify_authorizer): verify the presented service ticket
        (challenge round first — anti-replay), bind (entity, caps) to
        the transport peer, prove possession of the rotating secret
        back (mutual auth)."""
        import json as _json
        rep = _daemon_authorize(
            self.verifier, _json.loads(msg.blob.decode()), peer,
            msg.req_id, self._authed,
            lambda: self.c.key_server.export_rotating("osd"))
        try:
            self.msgr.send(peer, rep)
        except (KeyError, OSError, ConnectionError):
            pass

    def _auth_gate(self, peer: str, need: str) -> str | None:
        """None = allowed; else the EPERM reply string. ONE gate for
        both the client-op and store planes — RemoteStore._call and
        Client._op string-match these exact errors for their
        re-authorize retries (ref: OSDCap is_capable)."""
        sess = self._authed.get(peer)
        if sess is None:
            return "EPERM:unauthenticated"
        caps = sess["caps"].get("osd")
        # this tier serves ONE pool, named "default" (pool id 1), so
        # pool-scoped grants (`allow rw pool=default`) resolve here
        if caps is None or not caps.allows(need, pool="default"):
            return (f"EPERM:denied need {need} "
                    f"(entity {sess['entity']})")
        return None

    @staticmethod
    def _op_need(kind: str) -> str:
        return "x" if kind == "cls" else \
            ("r" if kind in OSDDaemon._READ_KINDS else "w")

    def _on_client_op(self, peer: str, msg: MOSDOp) -> None:
        sub_ops: list[tuple[str, bytes]] | None = None
        if msg.kind == "batch":
            # coalesced dispatch (one frame, many PG ops — the client
            # groups small ops to the same primary): decode sub-ops up
            # front so caps are gated per sub-op need before anything
            # executes
            try:
                d = Decoder(msg.blob)
                sub_ops = d.list(
                    lambda dd: (dd.string(), dd.blob()))
            except Exception as e:   # noqa: BLE001 — reply, don't die
                try:
                    self.msgr.send(peer, MOSDOpReply(
                        msg.req_id, False, msg.kind,
                        err=f"{type(e).__name__}:{e}"))
                except (KeyError, OSError, ConnectionError):
                    pass
                return
        if self.verifier is not None:
            needs = {self._op_need(k) for k, _ in sub_ops} \
                if sub_ops is not None else {self._op_need(msg.kind)}
            deny = next((d for d in (self._auth_gate(peer, n)
                                     for n in sorted(needs))
                         if d is not None), None)
            if deny is not None:
                try:
                    self.msgr.send(peer, MOSDOpReply(
                        msg.req_id, False, msg.kind, err=deny))
                except (KeyError, OSError, ConnectionError):
                    pass
                return
        if msg.kind == "admin":
            # the operator side door bypasses the op queue (like the
            # asok): it must answer even when the queue is wedged.
            # Own thread — some admin views take the daemon lock,
            # which a mid-reconcile fold can hold for remote-rpc
            # timescales, and a reactor must never wait that out
            def _serve_admin():
                try:
                    d = Decoder(msg.blob)
                    rep = MOSDOpReply(msg.req_id, True, msg.kind,
                                      self._admin_cmd(d.string()))
                except Exception as e:  # noqa: BLE001 — reply, don't
                    rep = MOSDOpReply(msg.req_id, False, msg.kind,
                                      err=f"{type(e).__name__}:{e}")
                try:
                    self.msgr.send(peer, rep)
                except (KeyError, OSError, ConnectionError):
                    pass
            threading.Thread(target=_serve_admin, daemon=True).start()
            return
        # mClock SHARDED admission: PG ops hash by their leading PG id
        # to an op shard and queue under their QoS class; each shard
        # worker drains in tag order — during recovery a client op
        # waits behind at most one recovery batch grant OF ITS SHARD,
        # not the whole rebuild, and ops to independent PGs dispatch
        # concurrently. Client ops land in their PER-TENANT class (one
        # per client entity per shard), so a heavy tenant — hedged
        # duplicates and degraded decodes included — competes under
        # its own (ρ, w, λ) tags instead of starving the rest.
        t_enq = time.time()     # r15: the osd.queue span's start mark
        if sub_ops is None:
            shard = self._shard_of(self._op_ps(msg.blob))
            cls = "scrub" if msg.kind in ("deep_scrub", "repair") \
                else self._client_class(peer, shard)
            self._sched_enqueue(
                cls, lambda: self._serve_client_op(peer, msg, None,
                                                   t_enq=t_enq),
                shard=shard)
            return
        # batch frame: split the sub-ops by shard (a batch groups by
        # PRIMARY, so one frame may span PGs in different shards);
        # every shard executes its slots FIFO — per-PG order holds —
        # and the last shard to finish assembles + sends the reply
        groups: dict[int, list] = {}
        for slot, (kind, body) in enumerate(sub_ops):
            sh = self._shard_of(self._op_ps(body))
            groups.setdefault(sh.idx, []).append((slot, kind, body))
        if len(groups) == 1:
            shard = self.op_shards[next(iter(groups))]
            cls = self._client_class(peer, shard)
            self._sched_enqueue(
                cls, lambda: self._serve_client_op(peer, msg, sub_ops,
                                                   t_enq=t_enq),
                shard=shard)
            return
        join = _BatchJoin(self, peer, msg, len(sub_ops), len(groups),
                          t_enq=t_enq)
        for idx, items in groups.items():
            shard = self.op_shards[idx]
            cls = self._client_class(peer, shard)
            self._sched_enqueue(
                cls, lambda items=items: join.run(items), shard=shard)

    def _trace_enter(self, msg, t_enq: float | None):
        """One op frame's trace arrival on a shard worker: fold the
        client's cost snapshot (sampled first hops carry it), record
        the mClock queue wait as an `osd.queue` span, and return the
        activate() context manager execution should run under (a
        no-op manager when the frame is untraced)."""
        from ..utils.flight_recorder import activate
        ctx = msg.trace
        if ctx is None:
            return activate(None, None)
        if ctx.client_lat or ctx.client_suspects:
            self._note_client_costs(ctx)
        if ctx.sampled and t_enq is not None:
            from ..utils.flight_recorder import new_trace_id
            self.flight.record(ctx.trace_id, new_trace_id(),
                               ctx.parent_span_id, "osd.queue",
                               t_enq, max(0.0, time.time() - t_enq),
                               {"kind": msg.kind})
        return activate(ctx, self.flight)

    def _maybe_retro_trace(self, op, ctx, ps: int | None = None) -> None:
        """Retroactive capture (r15): an UNSAMPLED op that crossed the
        live complaint threshold converts its OpTracker events into
        retro.* ring spans under the carried trace id — `ceph_cli
        trace <id>` can then assemble a timeline nobody sampled.

        r18 closes the replica gap: the primary additionally asks the
        PG's acting set to publish matching retro.subop spans from
        their sub-op retro rings (fire-and-forget retro_publish store
        frames; the spans drain through each replica's OWN MgrReports
        under the deterministic retro root id), so the assembled
        timeline covers client + primary + replicas instead of
        reporting replica time as wire."""
        if (ctx is None or ctx.sampled or not op.done
                or op.duration <= self.op_tracker.complaint_time):
            return
        self.flight.record_tracked(op, ctx)
        if ps is None \
                or int(self.config["osd_subop_retro_ring"]) <= 0:
            return
        with self._lock:
            be = self.backends.get(ps)
            acting = list(dict.fromkeys(be.acting)) if be is not None \
                else []
        e = Encoder()
        e.u64(ctx.trace_id)
        body = e.bytes()
        n = len(self.osdmap.osd_up) if self.osdmap is not None else 0
        for o in acting:
            if not _valid_osd(o, n) or o == self.osd_id:
                continue
            try:
                # submit-and-cancel: the frame is transmitted now, the
                # window slot freed immediately, the reply dropped —
                # the publish happens replica-side regardless, and a
                # dead replica costs nothing here
                self.rpc.submit(
                    f"osd.{o}",
                    lambda rid, b=body: MStoreOp(rid, True,
                                                 "retro_publish",
                                                 b)).cancel()
            except (KeyError, OSError, ConnectionError):
                continue

    def _subop_note(self, ctx, kind: str, start_wall: float,
                    dur: float, apply_s: float) -> None:
        """Remember one completed UNSAMPLED sub-op keyed by its
        carried trace id (the minimal OpTracker-style event ring of
        the r18 satellite) — retro_publish converts matches into
        flight-ring spans when the origin op turns out slow."""
        cap = int(self.config["osd_subop_retro_ring"])
        if cap <= 0:
            return
        rec = {"tid": ctx.trace_id, "parent": ctx.parent_span_id,
               "kind": kind, "start": start_wall,
               "dur": dur, "apply": apply_s}
        with self._subop_ring_lock:
            self._subop_ring.append(rec)
            over = len(self._subop_ring) - cap
            if over > 0:
                del self._subop_ring[:over]

    def _retro_publish(self, trace_id: int) -> int:
        """Publish this daemon's remembered sub-op windows for one
        trace into its flight ring as retro.subop (+ nested
        retro.store.apply) spans under the deterministic retro root —
        they reach the monitors' assemblers through the normal
        MgrReport drain."""
        from ..utils.flight_recorder import new_trace_id, retro_root_id
        root = retro_root_id(trace_id)
        with self._subop_ring_lock:
            matches = [r for r in self._subop_ring
                       if r["tid"] == trace_id]
        for r in matches:
            sid = new_trace_id()
            self.flight.record(trace_id, sid, root, "retro.subop",
                               r["start"], r["dur"],
                               {"kind": r["kind"], "retro": True})
            if r["apply"] > 0:
                # the apply is the service tail (store-lock wait
                # precedes it)
                self.flight.record(
                    trace_id, new_trace_id(), sid,
                    "retro.store.apply",
                    r["start"] + max(0.0, r["dur"] - r["apply"]),
                    r["apply"])
        if matches:
            self.perf.inc("retro_subop_published", len(matches))
        return len(matches)

    def _serve_client_op(self, peer: str, msg: MOSDOp,
                         sub_ops, t_enq: float | None = None) -> None:
        with self._trace_enter(msg, t_enq):
            self._serve_client_op_inner(peer, msg, sub_ops)

    def _serve_client_op_inner(self, peer: str, msg: MOSDOp,
                               sub_ops) -> None:
        try:
            if sub_ops is not None:
                # per-sub-op fault isolation: one bad sub-op fails its
                # slot, not the frame (the client maps each slot back
                # to its op's retry state)
                e = Encoder()
                e.u32(len(sub_ops))
                for kind, body in sub_ops:
                    try:
                        sub_blob = self._one_client_op(peer, kind, body)
                        e.boolean(True).blob_ref(sub_blob).string("")
                    except Exception as err:   # noqa: BLE001
                        e.boolean(False).blob(b"").string(
                            f"{type(err).__name__}:{err}")
                blob = e.bytes()
            else:
                blob = self._one_client_op(peer, msg.kind, msg.blob)
            rep = MOSDOpReply(msg.req_id, True, msg.kind, blob)
        except Exception as e:   # noqa: BLE001 — reply, don't die
            rep = MOSDOpReply(msg.req_id, False, msg.kind,
                              err=f"{type(e).__name__}:{e}")
        try:
            self.msgr.send(peer, rep)
        except (KeyError, OSError, ConnectionError):
            pass

    def _one_client_op(self, peer: str, kind: str, body: bytes) -> bytes:
        from ..utils.flight_recorder import current
        from ..utils.tracing import span
        ps = self._op_ps(body)
        is_read = kind in self._READ_KINDS
        t0 = time.perf_counter()
        with span("osd.op", counters=self.perf, key="op_latency"):
            with self.op_tracker.create_op(
                    f"osd_op({kind}) client={peer}") as op:
                # DEBUG latency injection (osd_inject_op_delay, live
                # central config): the deterministic slowness source
                # the SLO-burn tests drive — inside the tracked op so
                # history/complaints/histograms all see it, before
                # the PG lock so independent PGs aren't convoyed
                inject = float(self.config["osd_inject_op_delay"])
                if inject > 0:
                    time.sleep(inject)
                # DEBUG CPU burn (osd_inject_cpu_burn, r19): a busy
                # spin INSIDE the osd.op span — the deterministic hot
                # loop the profile-attribution tests drive. The r15
                # taxonomy puts osd.op self-time in "other", so the
                # burn must surface there in the flame profile (and
                # in profile_diff's regression verdict)
                burn = float(self.config["osd_inject_cpu_burn"])
                if burn > 0:
                    t_burn = time.perf_counter() + burn
                    while time.perf_counter() < t_burn:
                        pass
                # per-PG execution lock, not the daemon lock: ops to
                # independent PGs really do run concurrently across
                # shards; reconcile/recovery exclude themselves per PG
                # (they take self._lock THEN the PG locks they touch)
                with self._pg_lock(ps):
                    op.mark_event("reached_pg")
                    blob = self._client_op(kind, body)
                op.mark_event("commit_sent")
        # r18: the read/write split the client_read/client_write SLO
        # feeds merge (same sample the op_latency pair took)
        self.perf.tinc("op_r_latency" if is_read else "op_w_latency",
                       time.perf_counter() - t0)
        self._maybe_retro_trace(op, current(), ps)
        self.perf.inc_many(
            (("op", 1),
             ("op_r" if is_read else "op_w", 1),
             ("op_in_bytes", len(body)),
             ("op_out_bytes", len(blob))))
        return blob

    SNAP_SEP = "@@snap."

    def _check_snapc(self, snapc: int) -> None:
        """Mutating client ops carry the client's snap context (ref:
        MOSDOp's SnapContext): if the client knows a newer snap_seq
        than this primary's map, executing now would skip the COW for
        that snap — refuse so the client retries after the map
        broadcast lands (there is no cross-connection ordering
        between mon→osd maps and client→osd ops)."""
        if snapc > self.osdmap.pools[1].snap_seq:
            raise RuntimeError(
                f"map lag: op snapc {snapc} > pool snap_seq "
                f"{self.osdmap.pools[1].snap_seq} "
                f"(epoch {self.osdmap.epoch})")

    def _snap_guard(self, ps: int, be, names) -> None:
        """Write-path COW (ref: PrimaryLogPG::make_writeable): before
        the FIRST mutation of a head after each pool snap, preserve
        its bytes as a clone object in the SAME PG (the reference
        keeps clones in the head's PG too — same hash, different snap
        id; the name suffix stands in for the snapid field)."""
        seq = self.osdmap.pools[1].snap_seq
        births = self.births.setdefault(ps, {})
        sets_ = self.snapsets.setdefault(ps, {})
        for name in sorted(names):
            if self.SNAP_SEP in name:
                continue            # clones never re-clone
            if name not in be.object_sizes:
                # creation: remember the snap era it was born in, so
                # reads at older snaps correctly say "didn't exist"
                births[name] = seq
                continue
            if births.get(name, 0) >= seq:
                continue            # born after the newest snap
            ss = sets_.setdefault(name, [])
            if ss and ss[-1][0] >= seq:
                continue            # newest snap already preserved
            data = be.read_object(name, dead_osds=set(self.suspect))
            clone = f"{name}{self.SNAP_SEP}{seq:08x}"
            be.write_objects({clone: bytes(np.asarray(data, np.uint8)
                                           .tobytes())},
                             dead_osds=set(self.suspect))
            ss.append((seq, births.get(name, 0)))

    def _snap_resolve(self, ps: int, be, name: str, sid: int):
        """State of `name` as of snap `sid`: the OLDEST clone with
        seq >= sid that existed at the snap, else the unmodified head
        (ref: PrimaryLogPG find_object_context SnapSet resolution)."""
        if sid not in self.osdmap.pools[1].snaps:
            if sid > self.osdmap.pools[1].snap_seq:
                # the client knows a newer snap than this primary's
                # map: TRANSIENT lag (mon->osd vs client->osd frames
                # have no ordering) — retryable, like _check_snapc
                raise RuntimeError(
                    f"map lag: snap {sid} > pool snap_seq "
                    f"{self.osdmap.pools[1].snap_seq}")
            raise KeyError(f"no snap {sid}")   # genuinely removed
        ss = self.snapsets.get(ps, {}).get(name, [])
        cands = [seq for seq, birth in ss if seq >= sid and birth < sid]
        if cands:
            clone = f"{name}{self.SNAP_SEP}{min(cands):08x}"
            return be.read_object(clone, dead_osds=set(self.suspect))
        if name in be.object_sizes \
                and self.births.get(ps, {}).get(name, 0) < sid:
            return be.read_object(name, dead_osds=set(self.suspect))
        raise KeyError(f"{name!r} did not exist at snap {sid}")

    def _snap_trim(self, ps: int, be) -> None:
        """Drop clones no live snap reads anymore (the snaptrim role,
        ref: PrimaryLogPG::trim_object) — driven off the committed
        map's pool.snaps on every map change. Failure-tolerant: a
        refused removal keeps the clone for the next trim."""
        live = self.osdmap.pools[1].snaps
        sets_ = self.snapsets.get(ps)
        if not sets_:
            return
        changed = False
        for name, ss in list(sets_.items()):
            keep: list[tuple[int, int]] = []
            prev = 0
            for c, birth in ss:  # ascending; clone c covers snaps
                # (prev_kept, c], minus snaps older than its birth era
                if any(prev < s <= c and s > birth for s in live):
                    keep.append((c, birth))
                    prev = c
                    continue
                try:
                    be.remove_objects(
                        [f"{name}{self.SNAP_SEP}{c:08x}"],
                        dead_osds=set(self.suspect))
                    changed = True
                except (KeyError, ConnectionError, OSError):
                    keep.append((c, birth))
                    prev = c
            if keep:
                sets_[name] = keep
            else:
                del sets_[name]
                changed = True
        if changed:
            self._persist_meta(ps)

    def _delete_objects(self, ps: int, be, names: list[str]) -> None:
        """ONE delete path for the wire op and the cls shim:
        COW-preserve heads a live snap still needs (make_writeable
        before the delete), logged remove, per-object side state
        dropped. IDEMPOTENT: already-absent names are skipped — a
        client retrying a delete whose reply was lost must see
        success, not KeyError (write/read are naturally retry-safe;
        delete earns it by tolerating ENOENT, the reference's rados
        semantics for a replayed delete)."""
        present = [n for n in names if n in be.object_sizes]
        if present:
            self._snap_guard(ps, be, present)
            be.remove_objects(present, dead_osds=set(self.suspect))
        for name in names:
            self.obj_kv.get(ps, {}).pop(name, None)
            self.births.get(ps, {}).pop(name, None)

    def _client_op(self, kind: str, body: bytes) -> bytes:
        import json as _json
        d = Decoder(body)
        ps = d.u32()
        if kind == "read_degraded":
            # degraded-read fast path: served by ANY reachable acting
            # member — the not-primary and WaitUpThru gates below
            # deliberately do not apply (a read mutates nothing and
            # the serving view is read-only; see _degraded_read_op)
            return self._degraded_read_op(ps, d)
        be = self.backends.get(ps)
        if be is None:
            raise RuntimeError(f"not primary for pg 1.{ps} "
                               f"(epoch {self.osdmap.epoch})")
        need_ut = self._interval_start.get(ps, 0)
        if int(self.osdmap.osd_up_thru[self.osd_id]) < need_ut:
            # WaitUpThru: serving a write before the monitors recorded
            # this interval's up_thru would create an interval nobody
            # can later prove went rw — park the op (client retries
            # until the committed map unblocks us)
            raise RuntimeError(
                f"pg 1.{ps} peering (wait_up_thru {need_ut}, "
                f"epoch {self.osdmap.epoch})")
        if kind in ("write", "write_at", "append"):
            # r21 failsafe hard-stop: the LOCAL store ratio, not the
            # map — a full disk must never take another byte even
            # when this daemon's map is stale. Deletes ("remove")
            # pass: freeing space is how a full cluster recovers.
            # The raise is the retryable park shape (like WaitUpThru):
            # the client parks the op, nothing surfaces as op_error.
            self._failsafe_gate(ps)
        if kind == "write":
            self._check_snapc(d.u64())
            objs = d.mapping(Decoder.string, Decoder.blob)
            self._snap_guard(ps, be, objs)

            def _meta_extra(wave_names):
                # the PG metadata rides the write fan-out transaction
                # itself (the pg_log-inside-the-transaction
                # discipline): one wave persists bytes AND the
                # metadata that proves them, halving the write path's
                # frame count vs the old separate _persist_meta pass.
                # Steady state ships a BOUNDED DELTA (entries since
                # the last full blob + applied cursors, O(window));
                # the full O(objects-in-PG) base goes out every
                # _META_DELTA_MAX entries — without this, per-write
                # metadata cost grows linearly with PG object count
                # and the write path degrades quadratically over a
                # sustained workload. Snap-era state (snapsets/births
                # beyond era 0) isn't delta-encoded: any pool with
                # snaps takes the full-persist path every time,
                # keeping COW restore semantics byte-identical.
                ent, base_head = self._meta_delta.get(ps, ([], -1))
                ent = ent + [(n, be.object_versions[n],
                              be.object_sizes[n]) for n in wave_names]
                full = (base_head < 0
                        or len(ent) >= _META_DELTA_MAX
                        or self.osdmap.pools[1].snap_seq > 0
                        or self.snapsets.get(ps)
                        or self.obj_kv.get(ps))
                if full:
                    blob = self._encode_meta(ps)
                    self._meta_delta[ps] = ([], be.pg_log.head)
                    kv = {PG_META_KEY: blob, PG_META_DELTA_KEY: b""}
                else:
                    self._meta_delta[ps] = (ent, base_head)
                    kv = {PG_META_DELTA_KEY:
                          self._encode_meta_delta(ps)}

                def add(shard, t):
                    t.omap_set(shard_cid(be.pg, shard),
                               "__pg_meta__", kv)
                return add
            fused = isinstance(be, ECBackend)
            kw = {"shard_txn_extra": _meta_extra} if fused else {}
            try:
                be.write_objects(objs, dead_osds=set(self.suspect),
                                 **kw)
            except (ConnectionError, OSError):
                # a shard holder died mid-fan-out: mark it suspect and
                # retry once degraded; the client write must not bounce
                self._mark_suspects(be)
                be.write_objects(objs, dead_osds=set(self.suspect),
                                 **kw)
            if not fused:
                self._persist_meta(ps)
            return b""
        if kind in ("write_at", "append"):
            # partial-stripe writes (r16): the backend routes each op
            # through the parity-delta RMW fast path (journaled, only
            # touched + parity shards move) or the full-stripe ladder
            self._check_snapc(d.u64())
            trips = d.list(lambda dd: (dd.string(), dd.u64(),
                                       dd.blob()))
            self._snap_guard(ps, be, [n for n, _o, _b in trips])
            ops = [(n, be.object_sizes.get(n, 0) if kind == "append"
                    else off, blob) for n, off, blob in trips]
            try:
                be.write_ranges(ops, dead_osds=set(self.suspect))
            except (ConnectionError, OSError):
                # a shard holder died mid-fan-out: suspect it and
                # retry once degraded — the delta path refuses a
                # degraded stripe, so the retry rides the full-stripe
                # RMW (and the journal's abort + superseded-version
                # guard keep any half-logged intents inert)
                self._mark_suspects(be)
                be.write_ranges(ops, dead_osds=set(self.suspect))
            self._persist_meta(ps)
            return b""
        if kind == "remove":
            self._check_snapc(d.u64())
            names = d.list(Decoder.string)
            try:
                self._delete_objects(ps, be, names)
            except (ConnectionError, OSError):
                # a shard holder died mid-fan-out: suspect it and
                # retry once degraded (the write path's rule;
                # _delete_objects is idempotent so the retry is safe)
                self._mark_suspects(be)
                self._delete_objects(ps, be, names)
            self._persist_meta(ps)
            return b""
        if kind == "read":
            name = d.string()
            data = be.read_objects(
                [name], dead_osds=set(self.suspect),
                helper_costs=self._helper_costs(be))[name]
            return np.asarray(data, np.uint8).tobytes()
        if kind == "readv":
            # batched read: ONE decode launch serves the whole name
            # group (read_objects stacks equal-length groups), where
            # per-name ops would launch one decode each
            names = d.list(Decoder.string)
            for n in names:
                if n not in be.object_sizes:
                    raise KeyError(n)
            got = be.read_objects(names, dead_osds=set(self.suspect),
                                  helper_costs=self._helper_costs(be))
            e = Encoder()
            e.list([np.asarray(got[n], np.uint8).tobytes()
                    for n in names], Encoder.blob_ref)
            return e.bytes()
        if kind == "snap_read":
            name, sid = d.string(), d.u64()
            data = self._snap_resolve(ps, be, name, sid)
            return np.asarray(data, np.uint8).tobytes()
        if kind == "rollback":
            # rados rollback: write the snap's state back onto the
            # head — itself COW-protected, so the pre-rollback head
            # is preserved if a newer snap needs it
            self._check_snapc(d.u64())
            name, sid = d.string(), d.u64()
            data = self._snap_resolve(ps, be, name, sid)
            self._snap_guard(ps, be, [name])
            be.write_objects(
                {name: np.asarray(data, np.uint8).tobytes()},
                dead_osds=set(self.suspect))
            self._persist_meta(ps)
            return b""
        if kind == "deep_scrub":
            res = be.deep_scrub(dead_osds=set(self.suspect))
            return _json.dumps(res, sort_keys=True).encode()
        if kind == "repair":
            res = be.repair_pg(dead_osds=set(self.suspect))
            self._persist_meta(ps)
            return _json.dumps(res, sort_keys=True).encode()
        if kind == "cls":
            from .objclass import cls_call
            self._check_snapc(d.u64())
            name, cname, method = d.string(), d.string(), d.string()
            out = cls_call(_PgClsView(self, ps, be), name, cname,
                           method, d.blob())
            self._persist_meta(ps)   # kv mutations ride the metadata
            return out
        raise ValueError(f"unknown client op {kind!r}")

    # -- degraded-read fast path (server side) -------------------------------

    def _degraded_view(self, ps: int, hints: set[int]):
        """READ-ONLY backend over the freshest quorum-visible PG
        metadata — what lets a surviving acting shard serve reads
        while the primary is down, unreachable, or still peering
        (WaitUpThru), instead of parking them behind activation and
        recovery (ROADMAP item 3; the online-EC characterization's
        degraded-read tail, arxiv 1709.05365).

        Correctness leans on the meta-rides-the-write discipline: an
        ACKED write persisted its (base, delta) metadata on every live
        shard in the same transaction wave as the bytes, so the
        freshest pair a MAJORITY gather can see always covers it —
        serving from that pair is read-your-acked-writes consistent.
        The view is rebuilt per op (never cached): a primary may have
        activated elsewhere and served writes since any cached gather.
        No collections are created, nothing is persisted, EIO repairs
        are disabled — only an activated primary mutates shards.
        Raises RuntimeError (retryable at the client) when the gather
        cannot reach quorum."""
        acting = self._acting(ps)
        blob, _local, quorum_ok = self._load_meta(
            ps, acting, suspect_extra=hints)
        if not quorum_ok:
            raise RuntimeError(f"pg 1.{ps} degraded read deferred "
                               f"(meta gather below quorum)")
        be = self._make_backend(ps, acting, ensure_collections=False)
        if blob is None:
            return be            # virgin PG: the name check KeyErrors
        base, delta_blob = blob
        d, v = self._meta_decoder(base)
        if v >= 3:
            d.u64()              # persist epoch (ranking already used it)
        be.object_sizes = d.mapping(Decoder.string, Decoder.u64)
        be.object_versions = d.mapping(Decoder.string, Decoder.u64)
        be.pg_log = PGLog.decode(d.blob())
        applied = d.list(Decoder.u64)
        meta_acting = d.list(Decoder.i32)
        # the v2 tail (snapsets/births/cls-kv) is deliberately not
        # decoded: plain reads need sizes/versions/cursors only;
        # snap_read stays on the activated-primary path
        applied = self._apply_meta_delta(
            delta_blob, be.object_sizes, be.object_versions,
            be.pg_log, applied)
        # adopt the RECORDED acting: that is the set the cursors (and
        # the shard bytes) were written against
        be.acting = list(meta_acting)
        be.shard_applied = list(applied)
        return be

    def _degraded_read_op(self, ps: int, d: Decoder) -> bytes:
        """Serve a `read_degraded` op: fetch any k fresh surviving
        shards and decode on device through the process-wide fused
        programs (r10), skipping every down/suspected/hinted member.
        The hint list carries the OSDs the client is routing around
        (its timed-out primary) — honored for this op only, never
        recorded into self.suspect. Reply encoding matches `readv`
        (list of blobs, in name order)."""
        names = d.list(Decoder.string)
        hints = {int(h) for h in d.list(Decoder.i32)}
        n_osds = len(self.osdmap.osd_up)
        dead = ({o for o in range(n_osds)
                 if not self.osdmap.osd_up[o]}
                | set(self.suspect) | hints)
        dead.discard(self.osd_id)   # our own store always answers us
        be = self.backends.get(ps)
        need_ut = self._interval_start.get(ps, 0)
        if be is not None \
                and int(self.osdmap.osd_up_thru[self.osd_id]) >= need_ut:
            # we ARE the activated primary: the normal engine serves
            # (a hedged duplicate landing here costs one decode, and
            # EIO repair stays on — we own the shards)
            src, repair = be, True
        else:
            self.perf.inc("degraded_view_builds")
            src, repair = self._degraded_view(ps, hints), False
        for n in names:
            if n not in src.object_sizes:
                raise KeyError(n)
        with self.perf.time("degraded_read_time"):
            try:
                # the repair-locality planner serves the degraded
                # gather too: a single-shard LRC loss touches one
                # local group instead of any-k, cost-biased by the
                # same complaint/latency memory as recovery
                got = src.read_objects(
                    names, dead_osds=dead, repair=repair,
                    helper_costs=self._helper_costs(src))
            except KeyError as e:
                # names were just checked, so this KeyError is a
                # SHARD-level store miss: the meta already names a
                # repointed, still-rebuilding slot (recovery in
                # flight) whose store lacks this object. Transient —
                # surface as retryable, never as no-such-object.
                raise RuntimeError(
                    f"pg 1.{ps} degraded read raced recovery ({e}); "
                    f"retry") from None
        self.perf.inc("op_degraded_read", len(names))
        e = Encoder()
        e.list([np.asarray(got[n], np.uint8).tobytes()
                for n in names], Encoder.blob_ref)
        return e.bytes()

    def _mark_suspects(self, be) -> None:
        n_osds = len(self.osdmap.osd_up) if self.osdmap is not None \
            else 0
        for osd in set(be.acting):
            if osd == self.osd_id or osd in self.suspect \
                    or not _valid_osd(osd, n_osds):
                continue
            try:
                self.rpc.call(f"osd.{osd}",
                              lambda rid: MStoreOp(rid, True, "exists",
                                                   RemoteStore._co("x")),
                              timeout=1.0)
            except (ConnectionError, KeyError, OSError):
                self.suspect.add(osd)

    # -- liveness ------------------------------------------------------------

    def _on_ping(self, peer: str, msg: MOSDPing) -> None:
        try:
            self.msgr.send(peer, MOSDPingReply(msg.stamp))
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_pong(self, peer: str, msg: MOSDPingReply) -> None:
        if peer.startswith("osd."):
            now = time.monotonic()
            self._last_pong[int(peer[4:])] = now
            # r22: the reply echoes OUR monotonic send stamp, so the
            # round trip needs no wire change and no clock agreement
            # (even cross-process CLOCK_MONOTONIC is one clock here).
            # Fast dispatch: the fold is a leaf-locked bucket add.
            if bool(self.config["osd_network_observability"]):
                self.link_tracker.note(peer, now - msg.stamp,
                                       channel="hb")

    def _maybe_scheduled_scrub(self) -> None:
        """Background scrub scheduling (ref: PG scrub scheduling off
        osd_scrub_min_interval / osd_deep_scrub_interval; the sim
        tier schedules in virtual time, this one on the heartbeat).
        Per primaried PG: shallow at osd_scrub_interval, deep at
        osd_deep_scrub_interval; results land in scrub_reports
        (served by the `dump_scrubs` admin command) and auto_repair
        honors osd_scrub_auto_repair."""
        ival = float(self.config["osd_scrub_interval"])
        deep_ival = float(self.config["osd_deep_scrub_interval"])
        if ival <= 0 and deep_ival <= 0:
            return
        if not self._lock.acquire(blocking=False):
            return                # never stall the heartbeat
        try:
            now = time.monotonic()
            # at most ONE PG per beat (a multi-PG deep sweep under the
            # daemon lock would block client ops for its whole
            # duration), and the MOST OVERDUE due PG wins — first-due
            # in dict order would starve later PGs whenever the
            # interval is shorter than n_pgs * heartbeat_interval
            due = []
            for ps, be in self.backends.items():
                deep_due = deep_ival > 0 and \
                    now - self._last_deep.get(ps, 0.0) >= deep_ival
                shallow_due = ival > 0 and \
                    now - self._last_scrub.get(ps, 0.0) >= ival
                if deep_due or shallow_due:
                    due.append((self._last_scrub.get(ps, 0.0), ps,
                                be, deep_due))
            if due:
                _, ps, be, deep_due = min(due)
                # stamp the ATTEMPT first: a persistently failing
                # scrub retries at its interval, not every beat
                # (the _restore_backoff lesson)
                self._last_scrub[ps] = now
                if deep_due:
                    self._last_deep[ps] = now
                # PG lock: client ops no longer ride the daemon lock,
                # so the scrub read sweep must exclude them itself
                with self._pg_lock(ps):
                    self._run_scheduled_scrub(ps, be, deep_due, now)
        finally:
            self._lock.release()

    def _run_scheduled_scrub(self, ps: int, be, deep_due: bool,
                             now: float) -> None:
        """Execute one due scrub. Caller holds self._lock + the PG
        lock (see _maybe_scheduled_scrub)."""
        try:
            if deep_due:
                rep = be.deep_scrub(
                    dead_osds=set(self.suspect))
                rep["kind"] = "deep"
                found = (rep["inconsistent"]
                         or rep.get("digest_mismatch"))
                if found and bool(
                        self.config["osd_scrub_auto_repair"]):
                    be.repair_pg(dead_osds=set(self.suspect))
                    rep["auto_repaired"] = True
            else:
                rep = be.shallow_scrub(
                    skip_slots={s for s, o in
                                enumerate(be.acting)
                                if o in self.suspect})
                rep["kind"] = "shallow"
            rep["at"] = now
            self.scrub_reports[ps] = rep
            bad = (rep.get("inconsistent") or rep.get("errors")
                   or rep.get("digest_mismatch"))
            if bad:
                self.c.log(f"{self.name}: scheduled "
                           f"{rep['kind']} scrub pg 1.{ps}: "
                           f"{len(bad)} inconsistenc(ies)")
        except Exception as e:   # noqa: BLE001 — scrub must
            self.c.log(f"{self.name}: scheduled scrub pg "
                       f"1.{ps} failed: {e}")  # not kill hb

    def _heartbeat_loop(self) -> None:
        beat = 0
        # interval/grace resolve through the daemon config each beat,
        # so a committed `config set osd_heartbeat_*` retunes a RUNNING
        # daemon (the md_config_obs_t role, no restart)
        while not self._stop.wait(self.config["osd_heartbeat_interval"]):
            beat += 1
            if beat % 4 == 0 and self.osdmap is not None \
                    and not self.osdmap.osd_up[self.osd_id]:
                # the map says we're down but we're clearly running:
                # re-assert boot until a committed map shows us up
                # (ref: OSD::start_boot retry — a single MOSDBoot can
                # be consumed by a monitor that loses leadership, or
                # race the down-mark commit; retrying self-heals both)
                for mon_name in self.c.mon_names():
                    try:
                        self.msgr.send(mon_name, MOSDBoot(self.osd_id))
                    except (KeyError, OSError, ConnectionError):
                        pass
            if beat % 4 == 0 and self.osdmap is not None \
                    and self._lock.acquire(blocking=False):
                try:
                    # retry deferred recoveries (a reconcile is cheap
                    # when everything already matches the map)
                    self._reconcile()
                except Exception as e:  # noqa: BLE001 — the heartbeat
                    self.c.log(f"{self.name}: reconcile retry "
                               f"failed: {e!r}")   # thread must not die
                finally:
                    self._lock.release()
            now = time.monotonic()
            for osd in self.c.osd_ids():
                if osd == self.osd_id:
                    continue
                if self.osdmap is not None \
                        and not self.osdmap.osd_up[osd]:
                    # the map already says down: pinging would only
                    # grow the lossless queue without bound and flood
                    # the peer with stale pings on revive
                    continue
                self._last_pong.setdefault(osd, now)
                try:
                    # stamp per send, not per sweep: an injected link
                    # delay sleeps THIS thread before the transmit, so
                    # a sweep-wide stamp would charge peer k's delay to
                    # every peer pinged after it (r22 netobs needs the
                    # RTT attributed to exactly the degraded link)
                    self.msgr.send(f"osd.{osd}",
                                   MOSDPing(time.monotonic()))
                except (KeyError, OSError, ConnectionError):
                    pass
                stale = now - self._last_pong[osd] \
                    > self.config["osd_heartbeat_grace"]
                if stale and osd not in self._reported:
                    self._reported.add(osd)
                    self.suspect.add(osd)
                    # heartbeat silence is the DownClock's suspect
                    # evidence (map still up — repair parks nothing
                    # yet; the mon's down mark starts the window)
                    self.repair_policy.note_suspect(osd)
                    # broadcast to EVERY monitor: whoever currently
                    # leads acts, so leader failover needs no OSD-side
                    # coordination (the reference forwards via the
                    # session mon the same way)
                    for mon_name in self.c.mon_names():
                        try:
                            self.msgr.send(mon_name, MOSDFailure(osd))
                        except (KeyError, OSError, ConnectionError):
                            pass
                elif not stale and osd in self._reported:
                    # the peer answered our PINGS again before any
                    # down-mark committed: clear the heartbeat
                    # suspicion and retract OUR report at the
                    # monitors — a transient stall (scheduler hiccup,
                    # load) must not degrade the peer forever. Gated
                    # on _reported, not suspect: store-RPC-failure
                    # suspicion (_mark_suspects) is different
                    # evidence that ping liveness does not refute.
                    self.suspect.discard(osd)
                    self._reported.discard(osd)
                    self.c.log(f"{self.name}: osd.{osd} answered "
                               "again; retracting failure report")
                    for mon_name in self.c.mon_names():
                        try:
                            self.msgr.send(mon_name,
                                           MOSDFailure(osd, alive=True))
                        except (KeyError, OSError, ConnectionError):
                            pass
                # r22: a link whose RTT ewma crosses the slow-ping
                # line is DownClock suspect evidence (r17) — the peer
                # is alive but its wire is sick, so repair planning
                # should treat it warily. Hysteresis: flag at the
                # threshold, clear at half, one policy note per flip.
                if bool(self.config["osd_network_observability"]):
                    thr_s = self._slow_ping_threshold_s()
                    ewma = self.link_tracker.ewma_s(f"osd.{osd}")
                    if ewma > thr_s:
                        if osd not in self._slow_links:
                            self._slow_links.add(osd)
                            self.repair_policy.note_slow_link(osd)
                            self.c.log(
                                f"{self.name}: slow link to osd.{osd}"
                                f" (rtt ewma {ewma * 1e3:.1f}ms > "
                                f"{thr_s * 1e3:.1f}ms)")
                    elif ewma < thr_s / 2 \
                            and osd in self._slow_links:
                        self._slow_links.discard(osd)
                        # heartbeat-silence suspicion is separate
                        # evidence; only clear when it isn't active
                        if osd not in self.suspect:
                            self.repair_policy.clock(
                                osd).clear_suspect()
            try:
                # r18: close the current metric-history interval (if
                # its wall-clock boundary passed) BEFORE reporting so
                # the fresh entry ships on this same beat
                self.metrics_history.maybe_tick()
                # r19: same rule for the CPU sampler's profile ring
                self.profiler.maybe_tick()
                self._maybe_mgr_report()
            except Exception as e:  # noqa: BLE001 — stats shipping
                # must never kill the heartbeat thread
                self.c.log(f"{self.name}: mgr report failed: {e!r}")
            # scrub LAST — after pings AND the report: this beat's
            # pings are already out so a long deep scrub cannot push
            # our liveness past peers' grace, and the report shipped
            # first so the same scrub cannot starve the MgrReport
            # pipe either (r22: the mon's slow-link verdict reads our
            # link claims; a multi-second TinStore deep scrub parked
            # here used to freeze them mid-degrade)
            self._maybe_scheduled_scrub()

    def _slow_ping_threshold_s(self) -> float:
        """The slow-link line in SECONDS, the same resolution the mon
        NetworkAggregator uses (mon_warn_on_slow_ping_time ms when
        set, else ratio x grace) — daemon and mon judge one line."""
        warn_ms = float(self.config["mon_warn_on_slow_ping_time"])
        if warn_ms > 0:
            return warn_ms / 1e3
        return (float(self.config["mon_warn_on_slow_ping_ratio"])
                * float(self.config["osd_heartbeat_grace"]))

    def _maybe_mgr_report(self) -> None:
        """Periodically ship this daemon's counters + op stats + the
        PG states it primaries to every monitor (the MMgrReport flow,
        ref: DaemonServer::handle_report): FULL dump every Nth report,
        bounded DELTA in between — the aggregator re-bases on fulls,
        so lost reports and monitor restarts self-heal without acks."""
        import json as _json

        from ..mgr.reports import FULL_EVERY
        from ..utils.perf_counters import dump_delta
        now = time.monotonic()
        if now - self._mgr_last_sent \
                < float(self.config["mgr_report_interval"]):
            return
        self._mgr_last_sent = now
        perf = self.perf_dump_all()
        self._mgr_seq += 1
        full = (self._mgr_last_perf is None
                or self._mgr_seq % FULL_EVERY == 0)
        report = {
            "name": self.name,
            "seq": self._mgr_seq,
            "kind": "full" if full else "delta",
            "perf": perf if full
            else dump_delta(self._mgr_last_perf, perf),
            "ops_in_flight": len(self.op_tracker._in_flight),
            "slow_ops": len(self.op_tracker.slow_ops()),
            "epoch": self.osdmap.epoch
            if self.osdmap is not None else 0,
            # r20: merged mClock class occupancy rides every report so
            # the mon-side aggregate (and `ceph_cli top`) can attribute
            # WHICH tenant is being throttled, not just who is slow
            "mclock": self.sched_dump(),
        }
        if full:
            report["schema"] = self.perf_schema_all()
        # r15: drain freshly finished flight-recorder spans into the
        # same pipe (bounded per report; the mon-side TraceAssembler
        # stitches rings across daemons into causal timelines)
        spans = self.flight.drain(512)
        if spans:
            report["spans"] = spans
        # r18: freshly recorded metric-history intervals ride along
        # (normally 0-1 entries per report) into the monitors'
        # TelemetryAggregators, plus the flight ring's overflow
        # accounting (the TRACE_RING_OVERFLOW source — a declared
        # gauge AND a report field, so the aggregation never scrapes
        # ring internals)
        history = self.metrics_history.drain_unshipped()
        if history:
            report["history"] = history
        fstats = self.flight.stats()
        self.perf.set("trace_dropped_unshipped",
                      fstats["dropped_unshipped"])
        report["flight"] = fstats
        # r19: freshly closed profile-ring intervals (span-tagged
        # stack deltas) + the sampler's accounting ride the same pipe
        # into the monitors' ProfileAggregators
        report["profile"] = {
            "entries": self.profiler.drain_unshipped(),
            "stats": self.profiler.stats()}
        # r21 capacity plane: raw statfs on EVERY report (the store
        # has its own lock — no daemon-lock hazard). The mon ladder
        # only ever acts on these claims, never on local guesses.
        try:
            report["statfs"] = self.store.statfs()
        except Exception:
            pass
        # r22 network plane: per-link RTT state + per-peer flow ride
        # every report (side-field like statfs/mclock — per-peer keys
        # are dynamic, so they must never be counter names). The OFF
        # arm (osd_network_observability=false) ships nothing, which
        # is what the overhead-parity bench measures against.
        if bool(self.config["osd_network_observability"]):
            report["network"] = {
                "links": self.link_tracker.dump(),
                "flow": self.msgr.flow_dump(),
            }
        self._mgr_last_perf = perf
        # PG states want the daemon lock; never stall the heartbeat
        # for them — a busy beat ships without, and the aggregator
        # keeps the previous claim
        if self._lock.acquire(blocking=False):
            try:
                report["pgs"] = self._pg_states()
                report["pool_bytes"] = self._pool_bytes()
                report["pool_objects"] = self._pool_objects()
            finally:
                self._lock.release()
        blob = _json.dumps(report, separators=(",", ":")).encode()
        self.perf.inc("mgr_reports_tx")
        for mon_name in self.c.mon_names():
            try:
                self.msgr.send(mon_name,
                               MMgrReport(0, True, report["kind"],
                                          blob))
            except (KeyError, OSError, ConnectionError):
                pass

    def kill(self) -> None:
        """SIGKILL: stop answering everything, drop RAM state."""
        self._stop.set()
        self.profiler.stop()
        self.asok.stop()
        self.msgr.shutdown()
        self.store.crash()

    def revive(self) -> "OSDDaemon":
        """Fresh process, same disk: remount and boot."""
        self.store.remount()
        fresh = OSDDaemon.__new__(OSDDaemon)
        fresh.__dict__.update(self.__dict__)
        fresh.msgr = Messenger(self.name, secret=self.c.secret,
                               compress=self.c.compress,
                               workers=self.c.msgr_workers,
                               uds=self.c.msgr_uds)
        fresh.rpc = _Rpc(fresh.msgr, MStoreReply.type_id)
        fresh.backends = {}
        fresh.snapsets = {}
        fresh.births = {}
        fresh.obj_kv = {}
        fresh._interval_start = {}
        fresh._last_acting = {}
        fresh.suspect = set()
        fresh._last_pong = {}
        fresh._peer_lat = {}
        fresh._client_lat = {}
        fresh._reported = set()
        fresh._stop = threading.Event()
        # auth sessions die with the process; rotating secrets are
        # re-fetched at boot (a revived daemon must not honor tickets
        # from before a rotation it slept through). _start() rebuilds
        # the daemon's own ClientAuth + auth rpc on the new messenger.
        fresh._authed = {}
        fresh._init_observability()
        if fresh.verifier is not None:
            from ..auth import ServiceVerifier
            fresh.verifier = ServiceVerifier(
                "osd", self.c.key_server.export_rotating("osd"))
        fresh._start()
        return fresh


class _MonConfigView:
    """Read-only config resolver for a monitor (r18): committed-map
    config KV (coerced through the option schema) over g_conf's
    file/default layers. Monitors never carried a per-daemon Config;
    the telemetry plane's live options (mgr_slo_rules,
    mgr_history_interval, ...) need the committed layer visible."""

    def __init__(self, mon: "MonDaemon"):
        self._mon = mon

    def get(self, name: str):
        from ..utils.config import g_conf
        osdmap = self._mon.osdmap
        kv = osdmap.config_kv if osdmap is not None else {}
        if name in kv:
            opt = g_conf.schema.get(name)
            return opt.coerce(kv[name]) if opt is not None \
                else kv[name]
        return g_conf.get(name)

    def __getitem__(self, name: str):
        return self.get(name)


class MonDaemon:
    """Monitor endpoint. The lowest rank BELIEVED ALIVE leads (rank
    election over real ping frames — ref: src/mon/Elector.cc's
    lowest-rank-wins outcome, with liveness standing in for the
    propose/ack rounds); map commits go through MULTI-PHASE Paxos over
    real frames (ref: src/mon/Paxos.cc collect/last/begin/accept/
    commit): a leader first COLLECTs a majority of promises at a
    rank-stamped proposal number — learning the quorum's committed
    state and re-driving any accepted-but-uncommitted value — and only
    then BEGINs new values; peons accept only at or above their
    promised pn. Safety does not rest on the election: two monitors
    that both believe they lead (boot grace, partition) arbitrate by
    pn, and a value accepted by a majority is visible to every later
    collect quorum (intersection), so a committed epoch can never be
    displaced. A minority-side leader never gets its collect majority,
    so it can neither commit nor adopt uncommitted state as durable.
    OSD reports are broadcast to every monitor and QUEUED by all of
    them; whoever currently leads proposes (a queued mutation whose
    precondition the committed map already satisfies rebases to a
    no-op), so leadership moves drop nothing."""

    def __init__(self, rank: int, cluster: "StandaloneCluster",
                 osdmap: OSDMap | None = None):
        self.rank = rank
        self.c = cluster
        self.name = f"mon.{rank}"
        self.msgr = Messenger(self.name, secret=cluster.secret,
                              compress=cluster.compress,
                              workers=cluster.msgr_workers,
                              uds=cluster.msgr_uds)
        self.osdmap = osdmap            # the COMMITTED map, only
        # -- acceptor state (the peon role) --
        self._promised = 0              # highest pn promised
        self._accepted: tuple[int, int, bytes] | None = None
        #                               # (pn, epoch, blob) uncommitted
        # -- proposer state (the leader role) --
        self._pn = 0                    # pn held after collect quorum
        self._pn_seen = 0               # highest pn observed anywhere
        self._collecting: list | None = None   # [pn, responders, best]
        self._inflight: tuple[int, int, bytes, list] | None = None
        #                               # (pn, epoch, blob, mutations)
        self._accepts: set[str] = set()
        # Serialized proposal pipe (one begin in flight at a time):
        # queued mutate closures rebase onto the LATEST committed map
        # before proposing, so in-flight proposals can never collide
        # on an epoch key or silently drop each other's mutations.
        self._mutations: list = []
        self._reporters: dict[int, set[str]] = {}
        # epoch -> encoded Incremental for recent consecutive commits
        # (the delta fan-out source; bounded, full maps cover evictions)
        self._inc_cache: dict[int, bytes] = {}
        self._lock = threading.RLock()
        self._peer_pong: dict[int, float] = {}
        # peers start PRESUMED ALIVE for one grace window: a freshly
        # (re)started monitor must not claim leadership over a living
        # lower rank it simply hasn't heard from yet (dual-leader
        # window). Death is proven by grace expiry, not assumed.
        self._boot = time.monotonic()
        self._stop = threading.Event()
        # observability: paxos/mon counters + the per-monitor
        # MgrReport aggregate every daemon broadcasts into (the mgr
        # DaemonStateIndex role — this tier has no separate mgr
        # daemon, disclosed in ARCHITECTURE.md)
        from ..mgr.reports import MgrReportAggregator
        from ..utils.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder(f"mon.{rank}")
                     .add_u64_counter("paxos_collects",
                                      "collect rounds started")
                     .add_u64_counter("paxos_begins",
                                      "begin batches proposed")
                     .add_u64_counter("paxos_commits",
                                      "commits this monitor drove")
                     .add_u64_counter("paxos_commits_folded",
                                      "commits learned from peers")
                     .add_u64_counter("paxos_nacks_rx",
                                      "rounds lost to a nack")
                     .add_u64_counter("map_broadcasts",
                                      "map fan-outs to subscribers")
                     .add_u64_counter("map_inc_broadcasts",
                                      "incremental (delta) map "
                                      "fan-outs to subscribers")
                     .add_u64_counter("map_full_serves",
                                      "full maps served on request "
                                      "(inc chain gap at a subscriber)")
                     .add_u64_counter("mgr_reports_rx",
                                      "MgrReports ingested")
                     .add_u64_counter("mon_cmds",
                                      "read-only commands answered")
                     .add_u64_counter("full_flag_flips",
                                      "capacity-ladder commits: any "
                                      "per-OSD nearfull/backfillfull/"
                                      "full state, the cluster FULL "
                                      "flag, or a pool-quota flag "
                                      "changed in the map")
                     .add_u64("osdmap_epoch", "committed map epoch")
                     .create_perf_counters())
        self.mgr = MgrReportAggregator()
        # r18: a monitor config view layering the COMMITTED map's
        # config KV over g_conf defaults — what lets `config set
        # mgr_slo_rules ...` retune a running monitor's telemetry
        # evaluation (daemons get the same via their own layered
        # config; monitors never built one)
        self.conf_view = _MonConfigView(self)
        # r15: per-monitor trace assembler — every monitor stitches
        # the span streams riding the MgrReport pipe independently,
        # so any one of them can answer `ceph_cli trace`; r18 gives it
        # the config view so its continuous critical-path profile
        # aligns with the telemetry plane's history intervals
        from ..mgr.tracing import TraceAssembler
        self.traces = TraceAssembler(config=self.conf_view)
        # r18 telemetry plane: every monitor independently folds the
        # history entries riding MgrReports into cluster time-series,
        # merged quantiles, SLO burn verdicts, and the observed-
        # client-latency feed
        from ..mgr.telemetry import TelemetryAggregator
        self.telemetry = TelemetryAggregator(config=self.conf_view)
        from ..utils.perf_counters import MetricsHistory
        self.metrics_history = MetricsHistory(
            lambda: {self.perf.name: self.perf.dump(),
                     "msgr": self.msgr.perf.dump()},
            config=self.conf_view)
        # r19 continuous profiling: every monitor folds the profile
        # entries riding MgrReports into cluster/per-daemon flame
        # profiles, and is a profiled citizen itself (its own sampler
        # ticks on the self-report cadence)
        from ..mgr.profiles import ProfileAggregator
        from ..utils.profiler import SamplingProfiler
        self.profiles = ProfileAggregator(config=self.conf_view)
        self.profiler = SamplingProfiler(self.name,
                                         config=self.conf_view).start()
        # r22 network observability: every monitor independently folds
        # the links+flow claims riding MgrReports into the cluster
        # link matrix — serves dump_osd_network, raises
        # OSD_SLOW_PING_TIME, and feeds link_cost to the consumers
        from ..mgr.netobs import NetworkAggregator
        self.netobs = NetworkAggregator(config=self.conf_view)
        self._mgr_seq = 0
        self._mgr_last_sent = 0.0
        from ..utils.admin_socket import AdminSocket
        self.asok = AdminSocket(cluster.asok_path(self.name))
        for _cmd in ("status", "health", "health detail", "prometheus",
                     "perf dump", "perf schema", "report dump",
                     "mon_status", "log dump", "autoscale status",
                     "telemetry", "slo", "top", "profile", "df",
                     "dump_osd_network"):
            self.asok.register(_cmd,
                               lambda args, c=_cmd: self._mon_cmd_obj(c))
        # argumented: `trace slow` / `trace list` / `trace <id-hex>`
        self.asok.register(
            "trace",
            lambda args: self._mon_cmd_obj(("trace " + args).strip()),
            "assembled distributed traces: slow | list | <trace-id>")
        # argumented; longest-prefix dispatch keeps it ahead of the
        # bare `profile` (the r18 critical-path series)
        self.asok.register(
            "profile cpu",
            lambda args: self._mon_cmd_obj(
                ("profile cpu " + args).strip()),
            "cluster CPU flame profiles (r19): [daemon] "
            "[--collapsed|--speedscope]")
        self.asok.start()
        m = self.msgr
        m.register_handler(MMgrReport.type_id, self._on_mgr_report)
        m.register_handler(MMonCmd.type_id, self._on_mon_cmd)
        m.register_handler(MOSDFailure.type_id, self._on_failure)
        m.register_handler(MOSDBoot.type_id, self._on_boot)
        m.register_handler(MOSDAlive.type_id, self._on_alive)
        m.register_handler(MMonCollect.type_id, self._on_collect)
        m.register_handler(MMonLast.type_id, self._on_last)
        m.register_handler(MMonBegin.type_id, self._on_begin)
        m.register_handler(MMonAcceptPn.type_id, self._on_accept)
        m.register_handler(MMonCommit.type_id, self._on_commit)
        m.register_handler(MMonNack.type_id, self._on_nack)
        m.register_handler(MMonSyncReq.type_id, self._on_sync_req)
        m.register_handler(MOSDMapRequest.type_id, self._on_map_request)
        m.register_handler(MMonJoin.type_id, self._on_mon_join)
        m.register_handler(MOsdAdmin.type_id, self._on_osd_admin)
        # cephx service (ref: AuthMonitor + CephxServiceHandler).
        # Every monitor serves auth against the shared KeyServer (its
        # state is cluster bootstrap config here; KeyServer paxos
        # replication is out of this tier's scope, disclosed).
        self.auth_svc = None
        self.verifier = None
        self._authed: dict[str, dict] = {}
        if cluster.key_server is not None:
            from ..auth import AuthService, ServiceVerifier
            self.auth_svc = AuthService(cluster.key_server)
            # the monitor is itself a ticket-gated service: admin ops
            # (pool snaps, central config) need a mon ticket with w
            self.verifier = ServiceVerifier(
                "mon", cluster.key_server.export_rotating("mon"))
            m.register_handler(MAuthOp.type_id, self._on_auth)
        m.register_handler(MPoolOp.type_id, self._on_pool_op)
        m.register_handler(MPoolQuotaOp.type_id, self._on_pool_quota)
        m.register_handler(MConfigOp.type_id, self._on_config_op)
        m.register_handler(MOSDPing.type_id, self._on_ping)
        m.register_handler(MOSDPingReply.type_id, self._on_pong)
        self._hb = threading.Thread(target=self._mon_hb_loop,
                                    daemon=True)
        self._hb.start()

    # -- election (rank + liveness, gated on monmap membership) --------------

    def _members(self) -> list[int]:
        """Quorum membership from the COMMITTED map (the monmap role).
        Before any map is known (cluster bootstrap), every constructed
        monitor is presumed a member."""
        if self.osdmap is not None:
            return self.osdmap.mon_members
        return [m.rank for m in self.c.mons]

    def _alive_ranks(self) -> set[int]:
        mem = set(self._members())
        now = time.monotonic()
        alive = {self.rank} & mem
        for mon in self.c.mons:
            r = mon.rank
            if r == self.rank or r not in mem:
                continue
            last = self._peer_pong.get(r, self._boot)
            if now - last <= self.c.hb_grace:
                alive.add(r)
        return alive

    def is_leader(self) -> bool:
        """Lowest alive MEMBER leads; a removed monitor can never lead
        (nor count itself toward any quorum) even while its process
        is still running."""
        alive = self._alive_ranks()
        return bool(alive) and self.rank == min(alive)

    def _on_ping(self, peer: str, msg: MOSDPing) -> None:
        if peer.startswith("mon."):
            # a ping from a monitor proves it alive RIGHT NOW — record
            # it so a revived lower rank is seen leading within one of
            # ITS heartbeats instead of one of ours (shrinks the
            # dual-leader window to the revive→first-ping gap)
            self._peer_pong[int(peer[4:])] = time.monotonic()
        try:
            self.msgr.send(peer, MOSDPingReply(msg.stamp))
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_pong(self, peer: str, msg: MOSDPingReply) -> None:
        if peer.startswith("mon."):
            self._peer_pong[int(peer[4:])] = time.monotonic()

    def _mon_hb_loop(self) -> None:
        # ping FIRST, wait after: a freshly revived monitor must
        # announce itself before the first interval elapses, or the
        # old leader keeps leading a full heartbeat longer than needed
        while not self._stop.is_set():
            if getattr(self.c, "mons", None) is None:
                # cluster constructor still building the quorum
                self._stop.wait(0.02)
                continue
            for mon in self.c.mons:
                if mon.rank == self.rank or mon._stop.is_set():
                    continue
                try:
                    self.msgr.send(mon.name,
                                   MOSDPing(time.monotonic()))
                except (KeyError, OSError, ConnectionError):
                    pass
            # drive the Paxos machine: a leader retransmits its
            # outstanding collect/begin (their frames may have died
            # with a connection — both are idempotent at the peon),
            # collects when it holds no pn, proposes when the pipe is
            # idle. A NON-leader abandons proposer state so it can't
            # duel the real leader's pn (its mutations requeue and
            # re-propose if leadership ever returns).
            if self.is_leader():
                # r21 capacity ladder: only the leader evaluates — a
                # queued mutation from a stale evaluation rebases to a
                # no-op against the committed map anyway
                try:
                    self._capacity_tick()
                except Exception:  # noqa: BLE001 — the ladder must
                    pass           # never kill the mon heartbeat
                with self._lock:
                    col = self._collecting
                    infl = self._inflight
                    active = self._pn != 0
                if col is not None:
                    self._send_peers(MMonCollect(col[0]))
                elif infl is not None:
                    self._send_peers(MMonBegin(*infl[:3]))
                elif not active:
                    self._start_collect()
                else:
                    self._try_propose()
            else:
                with self._lock:
                    if self._collecting is not None \
                            or self._inflight is not None or self._pn:
                        self._abandon_locked()
                    # prune queued mutations the committed map already
                    # carries: a mon that never leads must not hoard
                    # no-op closures forever
                    if self._mutations and self.osdmap is not None:
                        base = self.osdmap
                        raw = base.encode()
                        keep = []
                        for mutate in self._mutations:
                            cand = OSDMap.decode(raw)
                            mutate(cand)
                            if cand.epoch != base.epoch:
                                keep.append(mutate)
                        self._mutations = keep
            try:
                self._self_report(broadcast=True)
            except Exception:    # noqa: BLE001 — observability must
                pass             # never kill the mon heartbeat
            if self._stop.wait(self.c.hb_interval):
                return

    # -- shared helpers ------------------------------------------------------

    def _majority(self) -> int:
        return len(self._members()) // 2 + 1

    def _send_peers(self, msg: Message) -> None:
        for mon in self.c.mons:
            if mon is not self and not mon._stop.is_set():
                try:
                    self.msgr.send(mon.name, msg)
                except (KeyError, OSError, ConnectionError):
                    pass

    def _committed_pair(self) -> tuple[int, bytes]:
        """Caller holds the lock. (0, b'') = no committed map yet."""
        if self.osdmap is None:
            return 0, b""
        return self.osdmap.epoch, self.osdmap.encode()

    def _fold_committed_locked(self, epoch: int, blob: bytes) -> None:
        """Adopt a COMMITTED map learned from a peer (Last/Nack/
        Commit frames carry one). Commit adoption is always safe —
        a majority durably accepted it — and monotonic by epoch."""
        if epoch and (self.osdmap is None or epoch > self.osdmap.epoch):
            old = self.osdmap
            self.osdmap = OSDMap.decode(blob)
            self._note_inc_locked(old, self.osdmap)
        if self._accepted is not None and self.osdmap is not None \
                and self._accepted[1] <= self.osdmap.epoch:
            self._accepted = None    # superseded by a commit
        if self._inflight is not None and self.osdmap is not None \
                and self._inflight[1] <= self.osdmap.epoch:
            # our in-flight value's epoch just committed (ours or a
            # rival's body): the round is over — requeue its mutations
            # for a rebase so late replayed accepts can't resurrect it
            self._mutations = self._inflight[3] + self._mutations
            self._inflight = None
            self._accepts = set()

    def _abandon_below_locked(self, pn: int) -> None:
        """Caller holds the lock, having just promised `pn`. ANY of
        our proposer rounds below it — held pn, outstanding collect,
        in-flight begin — can no longer win and must die NOW: a
        collect completed after the higher promise would let us
        begin/self-accept BELOW our own promise, downgrading the
        accepted-pn of a value a later quorum relies on (acceptor
        monotonicity is what the safety argument rests on)."""
        if (self._pn and self._pn < pn) \
                or (self._collecting is not None
                    and self._collecting[0] < pn) \
                or (self._inflight is not None
                    and self._inflight[0] < pn):
            self._abandon_locked()

    def _abandon_locked(self) -> None:
        """Caller holds the lock. Drop proposer state; REQUEUE any
        in-flight mutations at the front of the pipe (each mutate
        closure re-checks its precondition, so one the winning leader
        already committed rebases to a no-op). A lost round must never
        silently drop a mutation: a lost MOSDBoot would leave a
        revived OSD down forever (it boots exactly once)."""
        if self._inflight is not None:
            self._mutations = self._inflight[3] + self._mutations
        self._inflight = None
        self._collecting = None
        self._accepts = set()
        self._pn = 0

    # -- acceptor (peon) side ------------------------------------------------

    def _on_collect(self, peer: str, msg: MMonCollect) -> None:
        reply: Message
        with self._lock:
            self._pn_seen = max(self._pn_seen, msg.pn)
            if msg.pn >= self._promised:
                self._promised = msg.pn
                self._abandon_below_locked(msg.pn)
                apn, aep, ablob = self._accepted or (0, 0, b"")
                cep, cblob = self._committed_pair()
                reply = MMonLast(msg.pn, apn, aep, ablob, cep, cblob)
            else:
                reply = MMonNack(msg.pn, self._promised,
                                 *self._committed_pair())
        try:
            self.msgr.send(peer, reply)
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_begin(self, peer: str, msg: MMonBegin) -> None:
        reply: Message
        with self._lock:
            self._pn_seen = max(self._pn_seen, msg.pn)
            committed = self.osdmap.epoch if self.osdmap else 0
            if msg.pn < self._promised or msg.epoch <= committed:
                # promised a higher round, or the value's epoch is
                # already committed (stale/replayed begin): refuse,
                # teaching the proposer our promise + committed map
                reply = MMonNack(msg.pn, self._promised,
                                 *self._committed_pair())
            else:
                self._promised = msg.pn
                self._abandon_below_locked(msg.pn)
                self._accepted = (msg.pn, msg.epoch, msg.map_bytes)
                reply = MMonAcceptPn(msg.pn, msg.epoch)
        try:
            self.msgr.send(peer, reply)
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_commit(self, peer: str, msg: MMonCommit) -> None:
        with self._lock:
            fresh = self.osdmap is None \
                or msg.epoch > self.osdmap.epoch
            self._fold_committed_locked(msg.epoch, msg.map_bytes)
        if fresh:
            self.perf.inc("paxos_commits_folded")
            self.perf.set("osdmap_epoch", msg.epoch)
            # peons broadcast too: if the committing leader dies
            # between its commit fan-out and its subscriber fan-out,
            # subscribers would otherwise strand on the old epoch
            # until the next commit (subscribers dedup by epoch)
            self._broadcast(msg.epoch)

    def _on_osd_admin(self, peer: str, msg: MOsdAdmin) -> None:
        """`ceph osd out/in/reweight` (ref: OSDMonitor::
        prepare_command): idempotent weight mutations through the
        same Paxos pipe as everything else; cephx-gated like every
        admin broadcast."""
        if self.osdmap is None:
            return
        if self._mon_admin_denied(peer, f"osd {msg.kind} {msg.osd}"):
            return
        kind, osd, weight = msg.kind, msg.osd, msg.weight
        if not 0 <= osd < len(self.osdmap.osd_weight):
            # bounds-check BEFORE queueing: an IndexError inside the
            # proposal pipe would drop co-queued mutations, and a
            # negative id would numpy-wrap onto the wrong OSD
            self.c.log(f"{self.name}: REJECT osd admin {kind} "
                       f"osd.{osd} (no such osd)")
            return
        self.c.log(f"{self.name}: osd admin {kind} osd.{osd}")

        def mutate(m: OSDMap) -> None:
            w = int(weight * 0x10000)
            if kind == "out":
                # ADMIN out is sticky: a later boot must not reverse
                # it the way it reverses the failure path's auto-out
                if m.osd_weight[osd] != 0:
                    m.mark_out(osd)
                    m.osd_admin_out.add(osd)
                elif osd not in m.osd_admin_out:
                    m.osd_admin_out.add(osd)
                    m._bump()
            elif kind == "in" and (m.osd_weight[osd] == 0
                                   or osd in m.osd_admin_out):
                m.osd_admin_out.discard(osd)
                if m.osd_weight[osd] == 0:
                    m.mark_in(osd, weight)
                else:
                    m._bump()
            elif kind == "reweight" and m.osd_weight[osd] != w:
                if w == 0:
                    # weight-to-zero must behave like `osd out`:
                    # mark_out also clears pg_upmap entries that
                    # would keep pinning slots to the drained OSD
                    # (upmap redirection bypasses CRUSH's zero-weight
                    # rejection), and it's sticky like out
                    m.mark_out(osd)
                    m.osd_admin_out.add(osd)
                else:
                    m.osd_weight[osd] = w
                    # a positive admin reweight is an explicit 'in':
                    # clear the sticky admin-out flag so a later
                    # failure auto-out can be reversed by boot again
                    m.osd_admin_out.discard(osd)
                    m._bump()
        self._commit(mutate)

    def _on_mon_join(self, peer: str, msg: MMonJoin) -> None:
        """Membership change (ref: MonmapMonitor::prepare_join): queue
        the idempotent mutation; whoever leads commits it. Quorum math
        (_members/_majority/election) follows the COMMITTED map, so
        the change takes effect exactly at commit — Paxos
        reconfiguration by committing the new config through the old
        quorum."""
        if self.osdmap is None:
            return
        rank, join = msg.rank, msg.join
        self.c.log(f"{self.name}: mon.{rank} "
                   f"{'joins' if join else 'leaves'} (from {peer})")

        def mutate(m: OSDMap) -> None:
            if join:
                m.mon_join(rank)
            else:
                m.mon_leave(rank)
        self._commit(mutate)

    def _on_auth(self, peer: str, msg: MAuthOp) -> None:
        """cephx endpoint (ref: AuthMonitor::prep_auth): hello /
        authenticate mint the auth ticket; tickets mints per-service
        tickets. Byte fields travel hex-armored in JSON."""
        import json as _json
        if msg.kind == "authorize":
            rep = _daemon_authorize(
                self.verifier, _json.loads(msg.blob.decode()), peer,
                msg.req_id, self._authed,
                lambda: self.c.key_server.export_rotating("mon"))
            try:
                self.msgr.send(peer, rep)
            except (KeyError, OSError, ConnectionError):
                pass
            return
        try:
            req = _json.loads(msg.blob.decode())
            svc = self.auth_svc
            if msg.kind == "hello":
                sc = svc.hello(req["entity"], bytes.fromhex(req["cc"]))
                out = {"sc": sc.hex()}
            elif msg.kind == "authenticate":
                out = svc.authenticate(req["entity"],
                                       bytes.fromhex(req["cc"]),
                                       bytes.fromhex(req["proof"]))
            elif msg.kind == "tickets":
                out = svc.get_service_tickets(
                    req["ticket"], bytes.fromhex(req["nonce"]),
                    bytes.fromhex(req["mac"]), req["services"])
            else:
                raise ValueError(f"unknown auth op {msg.kind!r}")
            rep = MAuthReply(msg.req_id, True, msg.kind,
                             _json.dumps(out).encode())
        except Exception as e:   # noqa: BLE001 — reply, don't die
            rep = MAuthReply(msg.req_id, False, msg.kind,
                             err=f"{type(e).__name__}:{e}")
        try:
            self.msgr.send(peer, rep)
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_sync_req(self, peer: str, msg) -> None:
        """A revived monitor asks for the current map; answer with the
        COMMITTED map only (an accepted-but-uncommitted value must
        never be served as durable state — the mon store sync role,
        ref: src/mon/Monitor.cc sync_start)."""
        with self._lock:
            epoch, blob = self._committed_pair()
        if epoch:
            try:
                self.msgr.send(peer, MMonCommit(epoch, blob))
            except (KeyError, OSError, ConnectionError):
                pass

    # -- observability (MgrReport aggregation + read-only commands) ----------

    def _on_mgr_report(self, peer: str, msg: MMgrReport) -> None:
        import json as _json
        try:
            report = _json.loads(msg.blob.decode())
            # r15: span streams ride the same pipe — fold them into
            # the trace assembler. Pure-trace reports (client flushes)
            # must NOT touch the perf aggregation (they carry no
            # counters and would churn the daemon staleness state).
            if report.get("spans"):
                self.traces.ingest(report["spans"])
            # r18: history entries, flight overflow accounting, and
            # client-shipped observed-latency histograms feed the
            # telemetry plane (same pipe, independent consumers)
            if report.get("history"):
                self.telemetry.ingest(report.get("name", "?"),
                                      report["history"])
            if report.get("flight") is not None:
                self.telemetry.note_flight(report.get("name", "?"),
                                           report["flight"])
            # r19: span-tagged profile deltas feed the flame
            # aggregation (same pipe, independent consumer)
            if report.get("profile"):
                self.profiles.ingest(report.get("name", "?"),
                                     report["profile"])
            if report.get("client_perf"):
                self.telemetry.ingest_client(report.get("name", "?"),
                                             report["client_perf"])
            # r22: links+flow claims feed the link matrix (same pipe,
            # independent consumer)
            if report.get("network"):
                self.netobs.ingest(report.get("name", "?"),
                                   report["network"])
            if report.get("kind") != "trace":
                self.mgr.ingest(report)
            self.perf.inc("mgr_reports_rx")
        except (ValueError, UnicodeDecodeError):
            pass                 # malformed report: drop, don't die

    def _self_report(self, broadcast: bool = False) -> None:
        """The monitor is a daemon too: fold its own counters into its
        aggregator (no wire hop — local ingest) and, on the
        mgr_report_interval cadence, ship them to peer monitors as a
        normal MMgrReport — so ANY monitor's `ceph status`/prometheus
        covers the whole control plane, not just itself. Broadcasts
        are throttled like OSD reports: a 12-daemon bench showed
        unthrottled per-beat self-reports (dump + schema + sealed
        frames ×peers ×4 Hz) costing real percent of the one core the
        data plane shares."""
        from ..utils.config import g_conf
        now = time.monotonic()
        if broadcast and now - self._mgr_last_sent \
                < float(g_conf["mgr_report_interval"]):
            return
        self._mgr_last_sent = now
        self._mgr_seq += 1
        report = {
            "name": self.name, "seq": self._mgr_seq, "kind": "full",
            "perf": {self.perf.name: self.perf.dump(),
                     "msgr": self.msgr.perf.dump()},
            "schema": {self.perf.name: self.perf.schema(),
                       "msgr": self.msgr.perf.schema()},
        }
        # r18: the monitor is a telemetry citizen too — on the
        # broadcast cadence, tick its own history ring, fold fresh
        # entries into its OWN aggregator (no wire hop) and ship them
        # to peers with the report
        if broadcast:
            try:
                self.metrics_history.maybe_tick()
                history = self.metrics_history.drain_unshipped()
                if history:
                    report["history"] = history
                    self.telemetry.ingest(self.name, history)
                # r19: the monitor's own CPU profile rides the same
                # cadence — folded locally, shipped to peers
                self.profiler.maybe_tick()
                pblock = {"entries": self.profiler.drain_unshipped(),
                          "stats": self.profiler.stats()}
                report["profile"] = pblock
                self.profiles.ingest(self.name, pblock)
                # r22: the monitor is a flow citizen too — it measures
                # no heartbeat links (empty links), but its per-peer
                # msgr ledger belongs in the cluster flow totals
                nblock = {"links": {}, "flow": self.msgr.flow_dump()}
                report["network"] = nblock
                self.netobs.ingest(self.name, nblock)
            except Exception:   # noqa: BLE001 — observability must
                pass            # not break the monitor's reporting
        self.mgr.ingest(report)
        if broadcast:
            import json as _json
            self._send_peers(MMgrReport(
                0, True, "full",
                _json.dumps(report,
                            separators=(",", ":")).encode()))

    def _mon_read_denied(self, peer: str) -> bool:
        """Read-only command gate: any mon session with r (the MonCap
        `allow r` the reference requires for status). The asok path
        never comes through here — local filesystem access IS the
        operator credential there, like the reference's asok."""
        if self.verifier is None:
            return False
        sess = self._authed.get(peer)
        caps = sess["caps"].get("mon") if sess else None
        return caps is None or not caps.allows("r")

    def _health_obj(self, detail: bool = True) -> dict:
        from ..mgr.health import health_checks
        from ..utils.config import g_conf
        res = health_checks(
            osdmap=self.osdmap,
            quorum=sorted(self._alive_ranks()),
            mon_members=self._members(),
            reports=self.mgr,
            stale_grace=float(g_conf["mgr_stale_report_grace"]),
            pg_num=self.c.pg_num,
            telemetry=self.telemetry,
            netobs=self.netobs)
        if not detail:
            for c in res["checks"]:
                c.pop("detail", None)
        return res

    def _status_obj(self) -> dict:
        alive = sorted(self._alive_ranks())
        with self._lock:
            epoch = self.osdmap.epoch if self.osdmap is not None else 0
            osds_up = int(sum(self.osdmap.osd_up)) \
                if self.osdmap is not None else 0
            osds_in = int(sum(1 for w in self.osdmap.osd_weight
                              if w > 0)) \
                if self.osdmap is not None else 0
            n_osds = len(self.osdmap.osd_up) \
                if self.osdmap is not None else 0
        counts: dict[str, int] = {}
        for st in self.mgr.pg_states().values():
            counts[st] = counts.get(st, 0) + 1
        health = self._health_obj(detail=False)
        return {
            "health": health["status"],
            "checks": [c["code"] for c in health["checks"]],
            "epoch": epoch,
            "num_osds": n_osds, "osds_up": osds_up,
            "osds_in": osds_in,
            "mon_members": self._members(),
            "mon_quorum": alive,
            "mon_leader": min(alive) if alive else None,
            "pg_states": counts,
            "pgs_total": self.c.pg_num,
            **self.mgr.totals(),
        }

    def _capacity_tick(self) -> None:
        """r21 full-ratio ladder (ref: OSDMonitor::update_full_status
        + get_full_ratios): leader-only heartbeat evaluation. Folds
        every OSD's latest statfs claim through the committed ratio
        ladder (mon_osd_nearfull_ratio / osd_backfillfull_ratio /
        mon_osd_full_ratio) into per-OSD states, derives the cluster
        FULL flag (any OSD at full) and pool-quota flags
        (quota_max_bytes/objects vs the MgrReport pool aggregates),
        and commits ONLY deltas — a queued closure rebases to a no-op
        when the committed map already agrees, so a quiet cluster
        proposes nothing."""
        if self.osdmap is None:
            return
        near = float(self.conf_view["mon_osd_nearfull_ratio"])
        bff = float(self.conf_view["osd_backfillfull_ratio"])
        full = float(self.conf_view["mon_osd_full_ratio"])
        states: dict[int, int] = {}
        up = self.osdmap.osd_up
        for name, st in self.mgr.statfs().items():
            if not name.startswith("osd."):
                continue
            osd_id = int(name[4:])
            if osd_id < len(up) and not up[osd_id]:
                # down OSD: its last claim is frozen history, not
                # capacity — a dead reporter must not hold a ladder
                # rung (ref: OSDMonitor skips down/out in
                # get_full_osd_counts)
                continue
            total = int(st.get("total", 0))
            if total <= 0:
                continue               # unbounded store: no ratio
            ratio = int(st.get("used", 0)) / total
            if ratio >= full:
                states[int(name[4:])] = FULL_FULL
            elif ratio >= bff:
                states[int(name[4:])] = FULL_BACKFILLFULL
            elif ratio >= near:
                states[int(name[4:])] = FULL_NEARFULL
        cluster_full = any(s >= FULL_FULL for s in states.values())
        pool_bytes = self.mgr.pool_bytes()
        pool_objects = self.mgr.pool_objects()
        full_pools: set[int] = set()
        for pid, p in self.osdmap.pools.items():
            qb, qo = int(p.quota_max_bytes), int(p.quota_max_objects)
            if (qb and pool_bytes.get(pid, 0) >= qb) \
                    or (qo and pool_objects.get(pid, 0) >= qo):
                full_pools.add(pid)
        cur = self.osdmap
        if (cur.osd_full_state == states
                and cur.cluster_full == cluster_full
                and cur.full_pools == full_pools):
            return
        self.perf.inc("full_flag_flips")
        self._commit(lambda m, s=dict(states), cf=cluster_full,
                     fp=tuple(sorted(full_pools)):
                     m.set_full_states(dict(s), cf, set(fp)))

    def _df_obj(self) -> dict:
        """`ceph df` (r21): per-OSD statfs + committed ladder state +
        per-pool usage vs quota — rendered from the same two sources
        the ladder itself uses (MgrReport claims, committed map), so
        the operator sees exactly what the mon decided from."""
        m = self.osdmap
        stat = self.mgr.statfs()
        osds: dict[str, dict] = {}
        tot_b = used_b = 0
        for name in sorted(stat):
            st = stat[name]
            total = int(st.get("total", 0))
            used = int(st.get("used", 0))
            ent = {"total": total, "used": used,
                   "avail": int(st.get("avail", 0)),
                   "ratio": round(used / total, 4) if total else 0.0}
            if name.startswith("osd.") and m is not None:
                ent["state"] = FULL_STATE_NAMES.get(
                    m.full_state_of(int(name[4:])), "ok")
            tot_b += total
            used_b += used
            osds[name] = ent
        pool_bytes = self.mgr.pool_bytes()
        pool_objects = self.mgr.pool_objects()
        pools: dict[str, dict] = {}
        if m is not None:
            for pid, p in sorted(m.pools.items()):
                pools[str(pid)] = {
                    "bytes": int(pool_bytes.get(pid, 0)),
                    "objects": int(pool_objects.get(pid, 0)),
                    "quota_max_bytes": int(p.quota_max_bytes),
                    "quota_max_objects": int(p.quota_max_objects),
                    "full": pid in m.full_pools}
        return {
            "epoch": m.epoch if m is not None else 0,
            "cluster_full": bool(m.cluster_full)
            if m is not None else False,
            "full_ratios": {
                "nearfull": float(
                    self.conf_view["mon_osd_nearfull_ratio"]),
                "backfillfull": float(
                    self.conf_view["osd_backfillfull_ratio"]),
                "full": float(self.conf_view["mon_osd_full_ratio"]),
                "failsafe": float(
                    self.conf_view["osd_failsafe_full_ratio"])},
            "total_bytes": tot_b,
            "total_used_bytes": used_b,
            "total_avail_bytes": max(0, tot_b - used_b),
            "osds": osds,
            "pools": pools,
        }

    def _mon_cmd_obj(self, kind: str):
        """ONE dispatcher for the wire MMonCmd and the monitor's admin
        socket — the `ceph status / health / prometheus` surface,
        rendered from the committed map + this monitor's own liveness
        view + MgrReport-aggregated REAL daemon counters."""
        from ..mgr import reports as _reports
        from ..utils.log import g_log
        self.perf.inc("mon_cmds")
        self.perf.set("osdmap_epoch",
                      self.osdmap.epoch if self.osdmap is not None
                      else 0)
        self._self_report()      # answer with our own counters fresh
        if kind == "status":
            return self._status_obj()
        if kind == "health":
            return self._health_obj(detail=False)
        if kind == "health detail":
            return self._health_obj(detail=True)
        if kind == "df":
            return self._df_obj()
        if kind == "prometheus":
            # r22: the link plane's bounded-cardinality exposition
            # (worst-N by p99) appends to the counter exposition
            return {"text": _reports.prometheus_text(self.mgr)
                    + self.netobs.prometheus_text()}
        if kind == "dump_osd_network" or kind == "netstat":
            # r22: the cluster link matrix (ref: the OSD-level
            # dump_osd_network, served cluster-wide here because the
            # aggregator already holds every daemon's claim)
            return self.netobs.dump()
        if kind == "perf dump":
            return {"cluster": self.mgr.cluster_perf(),
                    self.name: {self.perf.name: self.perf.dump(),
                                "msgr": self.msgr.perf.dump()}}
        if kind == "perf schema":
            return {self.perf.name: self.perf.schema(),
                    "msgr": self.msgr.perf.schema()}
        if kind == "report dump":
            return self.mgr.daemons()
        if kind == "mon_status":
            alive = sorted(self._alive_ranks())
            return {"rank": self.rank, "members": self._members(),
                    "quorum": alive,
                    "leader": min(alive) if alive else None,
                    "is_leader": self.is_leader(),
                    "epoch": self.osdmap.epoch
                    if self.osdmap is not None else 0}
        if kind == "log dump":
            return {"lines": g_log.dump_recent()}
        if kind == "autoscale status":
            from ..mgr.pg_autoscaler import autoscale_from_reports
            if self.osdmap is None:
                return []
            return autoscale_from_reports(self.mgr, self.osdmap)
        if kind == "telemetry":
            # r18: cluster time-series + merged quantiles + the
            # observed-client-latency feed + SLO verdicts
            return self.telemetry.dump()
        if kind == "slo":
            return {"rules": self.telemetry.slo_status(),
                    "burn_rate": self.telemetry.burn_rate(),
                    "regressions": self.telemetry.regressions(),
                    # r21: per-client capacity-stall accounting, so a
                    # flat write feed during a FULL window reads as
                    # "parked", not "idle" or "regressed"
                    "full_backoff": self.telemetry.full_backoff()}
        if kind == "top":
            # per-daemon rates over the newest history interval; the
            # r19 observability drop gauges ride along (sampler +
            # flight-ring loss is an operator-visible condition, not
            # a silent one)
            out = self.telemetry.top(reports=self.mgr)
            out["observability"] = {
                "flight_dropped_unshipped":
                    self.telemetry.flight_drops(),
                "profiler": self.profiles.stats(),
            }
            # r20: per-tenant mClock grant/throttle accounting folded
            # from the daemons' mclock report claims
            out["tenants"] = self.mgr.tenants()
            return out
        if kind == "profile cpu" or kind.startswith("profile cpu "):
            # r19 flame profiles: cluster/per-daemon span-tagged CPU
            # attribution from the daemons' sampling rings
            return self.profiles.cpu_cmd(
                kind[len("profile cpu"):].strip())
        if kind == "profile":
            # continuous critical-path attribution series (sampled
            # traces folded per interval — the drift view)
            return self.traces.profile()
        if kind == "trace list":
            return {"traces": self.traces.list_traces()}
        if kind == "trace slow":
            # slowest assembled traces with their critical-path
            # attribution — the cross-daemon complement of slow_ops
            return {"traces": self.traces.slow()}
        if kind.startswith("trace "):
            # `trace <id-hex>`: one assembled causal timeline +
            # attribution summary + Chrome trace-event JSON
            return self.traces.assemble(kind[len("trace "):].strip())
        raise ValueError(f"unknown mon command {kind!r}")

    def _on_mon_cmd(self, peer: str, msg: MMonCmd) -> None:
        import json as _json
        if self._mon_read_denied(peer):
            rep = MMonCmdReply(msg.req_id, False, msg.kind,
                               err="EPERM:need mon r")
        else:
            try:
                rep = MMonCmdReply(
                    msg.req_id, True, msg.kind,
                    _json.dumps(self._mon_cmd_obj(msg.kind),
                                sort_keys=True, default=str).encode())
            except Exception as e:   # noqa: BLE001 — reply, don't die
                rep = MMonCmdReply(msg.req_id, False, msg.kind,
                                   err=f"{type(e).__name__}:{e}")
        try:
            self.msgr.send(peer, rep)
        except (KeyError, OSError, ConnectionError):
            pass

    # -- proposer (leader) side ----------------------------------------------

    def _next_pn_locked(self) -> int:
        n = (self._pn_seen >> 8) + 1
        pn = (n << 8) | self.rank
        self._pn_seen = pn
        return pn

    def _start_collect(self) -> None:
        with self._lock:
            if self._collecting is not None:
                return
            pn = self._next_pn_locked()
            # self-promise: we are one acceptor of our own round, and
            # promising our own pn keeps a lower concurrent collector
            # from splitting us off its quorum
            self._promised = max(self._promised, pn)
            self._collecting = [pn, set(), None]
        self.perf.inc("paxos_collects")
        self._send_peers(MMonCollect(pn))

    def _on_last(self, peer: str, msg: MMonLast) -> None:
        begin = None
        with self._lock:
            self._pn_seen = max(self._pn_seen, msg.accepted_pn)
            col = self._collecting
            if col is None or col[0] != msg.pn:
                return           # stale round
            if int(peer[4:]) not in self._members():
                return           # non-member promise must not count
                                 # toward a collect quorum
            if col[0] < self._promised:
                # we promised a rival's higher pn mid-collect: this
                # round is dead (belt to _abandon_below_locked)
                self._abandon_locked()
                return
            col[1].add(peer)
            self._fold_committed_locked(msg.committed_epoch,
                                        msg.committed_blob)
            committed = self.osdmap.epoch if self.osdmap else 0
            if msg.accepted_pn and msg.accepted_epoch > committed \
                    and (col[2] is None or msg.accepted_pn > col[2][0]):
                col[2] = (msg.accepted_pn, msg.accepted_epoch,
                          msg.accepted_blob)
            if len(col[1]) + 1 < self._majority():
                return
            # collect quorum: we hold the round. Any value accepted by
            # a majority is guaranteed visible here (quorum
            # intersection) — re-drive the highest-pn uncommitted one
            # under OUR pn before proposing anything new, or a
            # committed-elsewhere value could be lost.
            self._pn = col[0]
            self._collecting = None
            best = col[2]
            if self._accepted is not None \
                    and self._accepted[1] > committed \
                    and (best is None or self._accepted[0] > best[0]):
                best = self._accepted
            if best is not None and best[1] > committed:
                self._inflight = (self._pn, best[1], best[2], [])
                self._accepts = set()
                self._accepted = (self._pn, best[1], best[2])
                begin = MMonBegin(self._pn, best[1], best[2])
        if begin is not None:
            self._send_peers(begin)
        else:
            self._try_propose()

    def _on_accept(self, peer: str, msg: MMonAcceptPn) -> None:
        committed = None
        with self._lock:
            if self._inflight is None or self._inflight[0] != msg.pn \
                    or self._inflight[1] != msg.epoch:
                return           # superseded / already committed
            if int(peer[4:]) not in self._members():
                return           # non-member accept must not count
            self._accepts.add(peer)
            # commit once, on reaching a majority (self included) —
            # only NOW does the proposer's own map advance
            # (propose-then-commit: a quorum-less leader's mutation
            # must never become its local state, or a later store
            # sync would make it durable without a majority)
            if len(self._accepts) + 1 < self._majority():
                return
            pn, epoch, blob, muts = self._inflight
            self._inflight = None
            self._accepts = set()
            if self.osdmap is not None and epoch <= self.osdmap.epoch:
                # a newer commit folded in while the accepts were in
                # flight (partition heal replays them late): NEVER
                # regress the committed map — requeue for rebase
                self._mutations = muts + self._mutations
            else:
                old = self.osdmap
                self.osdmap = OSDMap.decode(blob)
                self._note_inc_locked(old, self.osdmap)
                if self._accepted is not None \
                        and self._accepted[1] <= epoch:
                    self._accepted = None
                committed = (epoch, blob)
        if committed is not None:
            self.perf.inc("paxos_commits")
            self.perf.set("osdmap_epoch", committed[0])
            self._send_peers(MMonCommit(*committed))
            self._broadcast(committed[0])
            self._try_propose()

    def _on_nack(self, peer: str, msg: MMonNack) -> None:
        """We lost a round (higher promise out there) or proposed a
        stale epoch: adopt the refuser's committed map, stand down,
        and let the next heartbeat re-collect at a higher pn if we
        still lead. A nack for some EARLIER round (replayed across a
        heal) still teaches the committed map but must not abort the
        current healthy round."""
        with self._lock:
            if int(peer[4:]) not in self._members():
                # a non-member (e.g. a freshly booted, not-yet-joined
                # monitor whose promised pn a rogue collect raised)
                # must not abort a member round — same filter as
                # _on_last/_on_accept
                return
            self._pn_seen = max(self._pn_seen, msg.promised)
            self._fold_committed_locked(msg.committed_epoch,
                                        msg.committed_blob)
            current = msg.nacked and (
                (self._collecting is not None
                 and self._collecting[0] == msg.nacked)
                or (self._inflight is not None
                    and self._inflight[0] == msg.nacked)
                or self._pn == msg.nacked)
            if current:
                self._abandon_locked()
        if current:
            self.perf.inc("paxos_nacks_rx")

    def _commit(self, mutate) -> None:
        """Queue `mutate` on the serialized proposal pipe; the map
        advances only when a majority accepts (see _on_accept)."""
        with self._lock:
            self._mutations.append(mutate)
        if self.is_leader():
            self._try_propose()

    def _try_propose(self) -> None:
        """Start the next begin batch if the pipe is idle and we hold
        a collected pn: rebase every queued mutation onto the LATEST
        committed map, propose the combined candidate. A batch whose
        mutations all rebase to no-ops (the committed map already
        carries them) is dropped."""
        begin = None
        with self._lock:
            if self._inflight is not None or not self._pn \
                    or self._collecting is not None \
                    or not self._mutations or self.osdmap is None:
                return
            candidate = OSDMap.decode(self.osdmap.encode())
            batch = self._mutations
            self._mutations = []
            kept = []
            for mutate in batch:
                try:
                    mutate(candidate)
                    kept.append(mutate)
                except Exception as e:   # noqa: BLE001 — one poison
                    # mutation must not destroy its co-queued batch
                    # (nor the proposal pipe): drop it and rebuild
                    # the candidate (it may be HALF-mutated), then
                    # replay the survivors
                    self.c.log(f"{self.name}: DROP mutation "
                               f"({type(e).__name__}: {e})")
                    candidate = OSDMap.decode(self.osdmap.encode())
                    for ok_mut in kept:
                        ok_mut(candidate)
            batch = kept
            if candidate.epoch == self.osdmap.epoch:
                return
            epoch, blob = candidate.epoch, candidate.encode()
            self._inflight = (self._pn, epoch, blob, batch)
            self._accepts = set()
            self._accepted = (self._pn, epoch, blob)  # self-accept
            begin = MMonBegin(self._pn, epoch, blob)
        self.perf.inc("paxos_begins")
        self._send_peers(begin)

    def _note_inc_locked(self, old: OSDMap | None,
                         new: OSDMap) -> None:
        """Derive + cache the delta for a freshly adopted consecutive
        epoch (caller holds the lock). Non-consecutive adoption (store
        sync across a gap) just doesn't cache — subscribers on the
        old epoch will request a full map."""
        if old is None or new.epoch != old.epoch + 1:
            return
        self._inc_cache[new.epoch] = Incremental.diff(old, new).encode()
        while len(self._inc_cache) > 32:
            del self._inc_cache[min(self._inc_cache)]

    def _broadcast(self, epoch: int) -> None:
        """Fan the committed epoch to every subscriber: a DELTA when
        this monitor holds the consecutive incremental and the epoch
        is off the full-map cadence, the full map otherwise (ref:
        OSDMonitor send_incremental — full every Nth epoch or on
        request, deltas in between)."""
        from ..utils.config import g_conf
        with self._lock:
            if self.osdmap is None or self.osdmap.epoch != epoch:
                return
            inc = self._inc_cache.get(epoch)
            full_every = max(1, int(g_conf["mon_osdmap_full_every"]))
            if inc is not None and epoch % full_every:
                cls_, blob, ctr = MOSDIncMapMsg, inc, "map_inc_broadcasts"
            else:
                cls_, blob, ctr = (MOSDMapMsg, self.osdmap.encode(),
                                   "map_broadcasts")
        self.perf.inc(ctr)
        for peer in self.c.map_subscribers():
            try:
                self.msgr.send(peer, cls_(epoch, blob))
            except (KeyError, OSError, ConnectionError):
                pass

    def _on_map_request(self, peer: str, msg: MOSDMapRequest) -> None:
        """Serve the full committed map to a subscriber that could not
        chain an incremental (gap, fresh boot) — the on-request half
        of the full-map cadence."""
        with self._lock:
            if self.osdmap is None or self.osdmap.epoch <= msg.epoch:
                return
            epoch, blob = self.osdmap.epoch, self.osdmap.encode()
        self.perf.inc("map_full_serves")
        try:
            self.msgr.send(peer, MOSDMapMsg(epoch, blob))
        except (KeyError, OSError, ConnectionError):
            pass

    def _on_failure(self, peer: str, msg: MOSDFailure) -> None:
        # EVERY mon queues the mutation (reports are broadcast to all):
        # only the current leader proposes, so whoever leads when the
        # pipe drains carries it — a report consumed by a monitor that
        # loses leadership a beat later is not lost, and a duplicate
        # rebases to a no-op against the committed map.
        if self.osdmap is None:
            return
        with self._lock:
            osd = msg.failed
            if msg.alive:
                # retraction: the reporter heard the peer again
                self._reporters.get(osd, set()).discard(peer)
                return
            if not self.osdmap.osd_up[osd]:
                return
            rep = self._reporters.setdefault(osd, set())
            rep.add(peer)
            if len(rep) < self.c.min_reporters:
                return
            del self._reporters[osd]
        self.c.log(f"{self.name}: marking osd.{osd} down+out "
                   f"({self.c.min_reporters} reporters)")

        def mutate(m: OSDMap) -> None:
            # precondition re-checked so a rebase onto a map that
            # already carries the mark is a no-op, not a double bump
            if m.osd_up[osd]:
                m.mark_down(osd)
                m.mark_out(osd)
        self._commit(mutate)

    def _on_boot(self, peer: str, msg: MOSDBoot) -> None:
        if self.osdmap is None:
            return
        osd = msg.failed
        self.c.log(f"{self.name}: osd.{osd} boots")

        def mutate(m: OSDMap) -> None:
            if not m.osd_up[osd]:
                m.mark_up(osd)
            # boot reverses the failure path's auto-out, NEVER an
            # administrator's sticky `osd out` (ref: AUTOOUT flag)
            if m.osd_weight[osd] == 0 and osd not in m.osd_admin_out:
                m.mark_in(osd)
        self._commit(mutate)

    def _on_alive(self, peer: str, msg: MOSDAlive) -> None:
        """up_thru request (ref: OSDMonitor::prepare_alive): record
        the claimed epoch through the same Paxos pipe as every other
        map mutation — the commit IS the activation permission the
        requesting primary is waiting on. Monotone/idempotent, so a
        duplicate or stale request rebases to a no-op."""
        if self.osdmap is None:
            return
        osd, want = msg.osd, msg.want
        if not _valid_osd(osd, len(self.osdmap.osd_up)):
            return

        def mutate(m: OSDMap) -> None:
            m.record_up_thru(osd, want)
        self._commit(mutate)

    def _mon_admin_denied(self, peer: str, what: str) -> bool:
        """Admin-plane gate (ref: MonCap check in
        Monitor::_allowed_command): with cephx on, pool/config
        mutations from peers without a mon session carrying w are
        DROPPED (these frames are fire-and-forget broadcasts; the
        client's commit-wait surfaces the refusal as a timeout).
        Daemon-internal traffic (failure reports, boots, paxos) stays
        ungated at this tier — it rides the transport-level shared
        secret when one is configured."""
        if self.verifier is None:
            return False
        sess = self._authed.get(peer)
        caps = sess["caps"].get("mon") if sess else None
        if caps is None or not caps.allows("w"):
            self.c.log(f"{self.name}: DROP {what} from {peer} "
                       f"(mon caps: "
                       f"{'none' if sess is None else 'no w'})")
            return True
        return False

    def _on_pool_op(self, peer: str, msg: MPoolOp) -> None:
        if self.osdmap is None:
            return
        if self._mon_admin_denied(peer, f"pool op {msg.kind}"):
            return
        kind, snap = msg.kind, msg.snap_name
        self.c.log(f"{self.name}: pool op {kind} {snap!r} from {peer}")

        def mutate(m: OSDMap) -> None:
            # both are name-idempotent: a duplicate rebases to a no-op
            if kind == "mksnap":
                m.pool_mksnap(1, snap)
            elif kind == "rmsnap":
                m.pool_rmsnap(1, snap)
        self._commit(mutate)

    def _on_pool_quota(self, peer: str, msg: MPoolQuotaOp) -> None:
        """`ceph osd pool set-quota` (r21): commit the quota onto the
        map; the leader's next capacity tick evaluates it against the
        MgrReport pool aggregates and raises/clears POOL_FULL."""
        if self.osdmap is None:
            return
        if self._mon_admin_denied(peer, f"pool quota {msg.pool_id}"):
            return
        if msg.pool_id not in self.osdmap.pools:
            self.c.log(f"{self.name}: REJECT pool quota "
                       f"(no pool {msg.pool_id})")
            return
        self.c.log(f"{self.name}: pool {msg.pool_id} quota "
                   f"bytes={msg.max_bytes} objects={msg.max_objects} "
                   f"from {peer}")
        self._commit(lambda m, p=msg.pool_id, b=msg.max_bytes,
                     o=msg.max_objects: m.set_pool_quota(p, b, o))

    def _on_config_op(self, peer: str, msg: MConfigOp) -> None:
        """Centralized config mutation (the ConfigMonitor role): the
        KV rides the same Paxos-committed value as the map, so a
        `config set` is durable exactly when a majority accepted it
        and every daemon observes it through its map subscription."""
        if self.osdmap is None:
            return
        if self._mon_admin_denied(peer, f"config {msg.kind} {msg.key}"):
            return
        kind, key, value = msg.kind, msg.key, msg.value
        self.c.log(f"{self.name}: config {kind} {key}={value!r} "
                   f"from {peer}")

        def mutate(m: OSDMap) -> None:
            # value-idempotent: a duplicate rebases to a no-op
            if kind == "set":
                m.config_set(key, value)
            elif kind == "rm":
                m.config_rm(key)
        self._commit(mutate)

    def kill(self) -> None:
        self._stop.set()
        self.profiler.stop()
        self.asok.stop()
        self.msgr.shutdown()


class _WireAuth:
    """ClientAuth's transport: the three monitor-side auth methods as
    MAuthOp frames against whichever monitor answers (ref: MonClient
    hunting across monitors). The last answering monitor is sticky so
    a hello/authenticate pair lands on the SAME AuthService (each
    monitor keeps its own outstanding-challenge table)."""

    def __init__(self, cluster: "StandaloneCluster", rpc: _Rpc):
        self.c = cluster
        self.rpc = rpc
        self._sticky: str | None = None

    def _call(self, method: str, payload: dict) -> dict:
        import json as _json
        from ..auth import AuthError
        last = None
        mons = self.c.mon_names()
        if self._sticky in mons:
            mons.remove(self._sticky)
            mons.insert(0, self._sticky)
        for mon in mons:
            try:
                # short per-monitor timeout: a dead/partitioned
                # monitor must cost the hunt ~2s, not stall a caller
                # (possibly a daemon dispatch thread) for 5+
                rep = self.rpc.call(
                    mon, lambda rid: MAuthOp(
                        rid, True, method,
                        _json.dumps(payload).encode()),
                    timeout=2.0)
            except (ConnectionError, KeyError, OSError) as e:
                last = str(e)
                if self._sticky == mon:
                    self._sticky = None
                continue            # hunt the next monitor
            if rep.ok:
                self._sticky = mon
                return _json.loads(rep.blob.decode())
            raise AuthError(rep.err)   # auth refusal is terminal
        raise ConnectionError(f"no monitor answered auth: {last}")

    def hello(self, entity: str, cc: bytes) -> bytes:
        return bytes.fromhex(
            self._call("hello", {"entity": entity, "cc": cc.hex()})["sc"])

    def authenticate(self, entity: str, cc: bytes, proof: bytes) -> dict:
        return self._call("authenticate",
                          {"entity": entity, "cc": cc.hex(),
                           "proof": proof.hex()})

    def get_service_tickets(self, ticket: dict, nonce: bytes,
                            mac: bytes, services: list) -> dict:
        return self._call("tickets",
                          {"ticket": ticket, "nonce": nonce.hex(),
                           "mac": mac.hex(), "services": services})


def _valid_osd(osd: int, n_osds: int) -> bool:
    """False for CRUSH_ITEM_NONE holes / out-of-range ids: a degraded
    epoch's acting set can carry the 2^31-1 sentinel where no OSD
    could be chosen, and addressing "osd.<sentinel>" (or indexing
    osd_up with it) must never happen (shared by reconcile, suspect
    probing, and client primary lookup; peering.py applies the same
    predicate to its own sets)."""
    return 0 <= osd < n_osds


def _wire_authorize(cauth, rpc: _Rpc, peer: str, service: str,
                    async_refresh=None) -> None:
    """Present a `service` ticket to `peer` over MAuthOp("authorize"),
    running the daemon's anti-replay challenge round, then verify its
    mutual-auth proof; refresh the ticket once if its sealing secret
    rotated out. Shared by clients (osd + mon sessions) and by OSDs
    authorizing to peer OSDs. `async_refresh` marks a DISPATCH-PATH
    caller: a needed ticket refresh is delegated to it (background)
    and this attempt fails fast with ConnectionError instead of
    hunting monitors inline (see OSDDaemon._authorize_peer)."""
    import json as _json
    from ..auth import AuthError
    server_challenge = None
    refreshed = False
    for _ in range(4):
        # key snapshot: the reply must verify against the key that
        # built THIS authorizer — a concurrent ticket refresh (the
        # daemon prewarm thread, another dispatch thread) must not
        # turn a correct daemon reply into a fake mutual-auth failure
        az, key = cauth.authorizer_with_key(
            service, server_challenge=server_challenge)
        try:
            # short timeout: this can run from a daemon's dispatch
            # thread (peer store reads); a dead peer must not stall it
            rep = rpc.call(
                peer, lambda rid: MAuthOp(rid, True, "authorize",
                                          _json.dumps(az).encode()),
                timeout=2.0)
        except (ConnectionError, KeyError, OSError):
            return   # peer unreachable; the caller's op loop retargets
        if rep.ok:
            got = _json.loads(rep.blob.decode())
            if not cauth.verify_reply(
                    service, az, bytes.fromhex(got["reply_mac"]),
                    key=key):
                raise AuthError(
                    f"{peer} failed mutual auth (does not hold the "
                    "rotating secret)")
            return
        if rep.err.startswith("EAGAIN:challenge:"):
            server_challenge = rep.err.rsplit(":", 1)[1]
            continue
        if "rotated out" in rep.err and not refreshed:
            if async_refresh is not None:
                async_refresh()
                raise ConnectionError(
                    f"{service} ticket rotated out; refresh kicked, "
                    f"authorize to {peer} deferred")
            cauth.fetch_tickets([service])
            refreshed, server_challenge = True, None
            continue
        raise AuthError(rep.err)
    raise AuthError(f"authorize to {peer} did not converge")


class _WireOp:
    """One client op's retry state inside _run_ops.

    `names` (read kinds only) lets the op be re-issued as a
    `read_degraded` frame to a non-primary acting shard — the hedged /
    degraded dispatch paths; mutating ops never carry names and are
    never duplicated. `avoid` collects targets that transport-failed
    for THIS op; `try_degraded` marks that the primary path is parked
    (peering / not-primary / timed out) and the next round should go
    straight to a surviving shard."""

    __slots__ = ("kind", "ps", "body_fn", "blob", "last", "done",
                 "fatal", "names", "avoid", "try_degraded",
                 "full_wait", "full_pin_t")

    def __init__(self, kind: str, ps: int, body_fn, names=None):
        self.kind, self.ps, self.body_fn = kind, ps, body_fn
        self.blob: bytes = b""
        self.last = None
        self.done = False
        self.fatal: BaseException | None = None
        self.names: list[str] | None = names
        self.avoid: set[str] = set()
        self.try_degraded = False
        # r21: the map epoch an OSD failsafe-full bounce parked this
        # op at — the op sits out every round until a NEWER epoch
        # shows up (capacity-ladder commits bump it), then probes
        # once. full_pin_t (monotonic seconds at pin time) bounds the
        # park: a bounce whose cause clears before the ladder ever
        # commits it (sub-report-beat full window) produces NO newer
        # epoch, so a stale pin must eventually probe on its own
        self.full_wait: int | None = None
        self.full_pin_t: float | None = None


class _TracedCall:
    """A _PendingCall plus the client-side root span of its trace:
    whatever way the handle retires (wait / take / cancel / timeout),
    the span records EXACTLY ONCE — sampled frames record always,
    unsampled ones retroactively when they crossed the client's
    complaint threshold (the slow-op path `trace <id>` assembles)."""

    __slots__ = ("_cl", "_p", "ctx", "_name", "_tags", "_t0w",
                 "_t0m", "_done")

    def __init__(self, client: "Client", pend: _PendingCall,
                 ctx, name: str, tags: dict | None):
        self._cl, self._p, self.ctx = client, pend, ctx
        self._name, self._tags = name, tags
        self._t0w, self._t0m = time.time(), time.monotonic()
        self._done = False

    def _finish(self) -> None:
        ctx = self.ctx
        if self._done or ctx is None:
            return
        self._done = True
        dur = time.monotonic() - self._t0m
        retro = not ctx.sampled
        if retro and dur <= self._cl.op_tracker.complaint_time:
            return
        tags = dict(self._tags or {})
        if retro:
            tags["retro"] = True
        self._cl.flight.record(ctx.trace_id, ctx.parent_span_id, 0,
                               self._name, self._t0w, dur, tags)

    # the _PendingCall surface the dispatch/hedge loops drive --------------

    def wait(self, timeout: float = 10.0):
        try:
            return self._p.wait(timeout)
        finally:
            self._finish()

    def ready(self, timeout: float | None = 0.0) -> bool:
        return self._p.ready(timeout)

    def take(self):
        try:
            return self._p.take()
        finally:
            self._finish()

    def cancel(self) -> None:
        self._p.cancel()
        self._finish()

    def add_waiter(self, ev: threading.Event) -> None:
        self._p.add_waiter(ev)


class Client:
    """librados over the wire: locate the PG from the cached map, talk
    to its primary, retry on map change / primary death. Ops dispatch
    through a windowed in-flight pipeline (`window` ops / `window_bytes`
    payload budget) with per-primary frame coalescing — see
    _run_ops."""

    #: read kinds a client may duplicate (hedge) or re-route to a
    #: surviving shard as `read_degraded` — NEVER mutations (exactly-
    #: once would break) and not snap_read (snap state lives only at
    #: the activated primary)
    _HEDGE_KINDS = frozenset({"read", "readv"})

    def __init__(self, cluster: "StandaloneCluster", name: str = "client",
                 entity: str = "client.admin",
                 secret: bytes | None = None,
                 window: int | None = None,
                 window_bytes: int = 64 << 20,
                 hedge_delay_ms: float | None = None,
                 trace_sample_rate: float | None = None):
        from ..utils.flight_recorder import FlightRecorder
        from ..utils.op_tracker import OpTracker
        from ..utils.perf_counters import PerfCountersBuilder
        self.c = cluster
        self.msgr = Messenger(name, secret=cluster.secret,
                              compress=cluster.compress,
                              workers=cluster.msgr_workers,
                              uds=cluster.msgr_uds)
        self.rpc = _Rpc(self.msgr, MOSDOpReply.type_id,
                        window=cluster.op_window if window is None
                        else window,
                        window_bytes=window_bytes)
        self.osdmap: OSDMap | None = None
        self._lock = threading.Lock()
        # hedged-read knob: None resolves through the committed
        # central config (client_hedge_delay_ms) with the schema
        # default (0 = auto from latency history, < 0 = off)
        self.hedge_delay_ms = hedge_delay_ms
        # read-frame latency history: the OpTracker the auto hedge
        # delay derives from (submit->reply wall time per read frame)
        self.op_tracker = OpTracker(history_size=64,
                                    complaint_time=5.0)
        # r15 distributed tracing: the client is the trace ORIGIN — it
        # stamps a compact context on every op frame (live-resolved
        # sample rate decides eager recording; hedged/degraded
        # dispatches are always sampled) and keeps its own flight ring
        # of client.op/client.hedge root spans, flushed to the
        # monitors' assemblers after op rounds.
        self.trace_sample_rate = trace_sample_rate
        self.flight = FlightRecorder(name)
        self.last_trace_id: int = 0     # newest SAMPLED trace stamped
        self._trace_flushed = 0.0
        self._perf_shipped_count = 0    # op_lat samples last shipped
        self.perf = (PerfCountersBuilder("client")
                     .add_u64_counter("hedge_issued",
                                      "duplicate shard reads sent "
                                      "after the hedge delay")
                     .add_u64_counter("hedge_wins",
                                      "ops settled by the hedged "
                                      "duplicate first")
                     .add_u64_counter("hedge_losses",
                                      "hedges beaten by the primary "
                                      "reply (loser cancelled)")
                     .add_u64_counter("hedge_cancelled",
                                      "in-flight frames abandoned "
                                      "after the other side won or "
                                      "the round timed out")
                     .add_u64_counter("degraded_dispatch",
                                      "reads sent straight to a "
                                      "surviving shard (primary "
                                      "down/parked)")
                     .add_u64_counter("degraded_served",
                                      "ops settled by a degraded "
                                      "shard reply")
                     .add_u64_counter("link_cost_refreshes",
                                      "r22 link-cost feed pulls from "
                                      "the mon link matrix (TTL-"
                                      "gated, background)")
                     .add_u64_counter("link_cost_demotions",
                                      "fallback/hedge candidates "
                                      "ranked down because the link "
                                      "feed's cost exceeded the "
                                      "client's own EWMA view")
                     .add_time_avg("op_lat",
                                   "client-observed frame time "
                                   "(submit -> reply, wire and "
                                   "window wait included) — the r18 "
                                   "observed_client_latency feed",
                                   hist=True)
                     .add_time_avg("full_backoff_time",
                                   "wall time mutating ops sat parked "
                                   "behind a FULL cluster/pool flag "
                                   "or an OSD failsafe bounce (the "
                                   "RADOS full-wait contract: parked, "
                                   "never errored) — the SLO plane "
                                   "discloses these intervals instead "
                                   "of charging them to write latency",
                                   hist=True)
                     .create_perf_counters())
        # r21 FULL_TRY (ref: CEPH_OSD_FLAG_FULL_TRY): an admin client
        # sets this to push mutations through a map-level FULL flag
        # (deletes already pass — they free space); the OSD failsafe
        # still bounces when the local disk truly has no room
        self.full_try = False
        # per-target read-latency EWMA: orders the fallback/hedge
        # candidates ("next-best shard")
        self._lat_ewma: dict[str, float] = {}
        # complaint memory: targets that transport-failed or lost to a
        # hedge outright, pinned to the map epoch that named them —
        # later reads skip the hedge delay and go straight degraded
        # until a newer map (or a successful reply) clears the entry
        self._tgt_suspect: dict[str, int] = {}
        # r22 link-cost feed: worst measured cost per OSD (µs) pulled
        # from the mon link matrix — MEASURED wire health joining the
        # client's own op-latency inference in the fallback/hedge
        # ordering. TTL-gated and refreshed on a background thread
        # (single-flight): the read path only ever consults the cache.
        self._link_costs: dict[int, int] = {}
        self._link_costs_at = -1e9
        self._link_gate = threading.Lock()
        self.msgr.register_handler(MOSDMapMsg.type_id, self._on_map)
        self.msgr.register_handler(MOSDIncMapMsg.type_id,
                                   self._on_inc_map)
        # read-only monitor commands (status/health/prometheus) ride
        # their own correlation space
        self.mon_rpc = _Rpc(self.msgr, MMonCmdReply.type_id)
        self._cauth = None
        if cluster.key_server is not None:
            from ..auth import ClientAuth
            self.auth_rpc = _Rpc(self.msgr, MAuthReply.type_id)
            self._cauth = ClientAuth(
                _WireAuth(cluster, self.auth_rpc), entity,
                cluster.admin_secret if secret is None else secret)

    def _authorize(self, osd_name: str) -> None:
        _wire_authorize(self._cauth, self.auth_rpc, osd_name, "osd")

    def _ensure_mon_sessions(self) -> None:
        """Authorize with every live monitor before an admin broadcast
        (pool/config ops are dropped from unauthenticated peers).
        Re-run per call: monitor restarts silently void sessions, and
        admin ops are rare enough that one authorize round-trip per
        monitor is noise."""
        if self._cauth is None:
            return
        for mon in self.c.mon_names():
            _wire_authorize(self._cauth, self.auth_rpc, mon, "mon")

    def _on_map(self, peer: str, msg: MOSDMapMsg) -> None:
        with self._lock:
            if self.osdmap is None or msg.epoch > self.osdmap.epoch:
                self.osdmap = OSDMap.decode(msg.map_bytes)

    def _on_inc_map(self, peer: str, msg: MOSDIncMapMsg) -> None:
        """Clients ride the same delta subscription as OSDs: chain a
        consecutive incremental onto a clone, otherwise request the
        full map from the sending monitor."""
        with self._lock:
            cur = self.osdmap
            if cur is not None and msg.epoch <= cur.epoch:
                return
            if cur is not None and msg.epoch == cur.epoch + 1:
                inc = Incremental.decode(msg.map_bytes)
                if inc.base_epoch == cur.epoch:
                    self.osdmap = inc.apply(cur.shallow_clone())
                    return
            req_epoch = cur.epoch if cur is not None else 0
        try:
            self.msgr.send(peer, MOSDMapRequest(req_epoch))
        except (KeyError, OSError, ConnectionError):
            pass

    def _primary(self, ps: int) -> str:
        acting = self.osdmap.pg_to_up_acting_osds(1, ps)[2]
        if not acting or not _valid_osd(acting[0],
                                        len(self.osdmap.osd_up)):
            # empty, or an ITEM_NONE hole in a degraded epoch: no
            # serviceable primary — retry on the next map
            raise ConnectionError(f"pg 1.{ps} has no acting primary")
        return f"osd.{acting[0]}"

    def daemon(self, osd: int, cmd: str, timeout: float = 10.0):
        """`ceph daemon osd.N <cmd>` — daemon-addressed admin command
        (perf dump / dump_historic_ops / dump_ops_in_flight /
        slow_ops), served from the target's OpTracker/PerfCounters."""
        import json as _json
        e = Encoder()
        e.string(cmd)
        target = f"osd.{osd}"
        rep = self.rpc.call(
            target, lambda rid: MOSDOp(rid, True, "admin", e.bytes()),
            timeout=timeout)
        if not rep.ok and rep.err == "EPERM:unauthenticated" \
                and self._cauth is not None:
            self._authorize(target)
            rep = self.rpc.call(
                target,
                lambda rid: MOSDOp(rid, True, "admin", e.bytes()),
                timeout=timeout)
        if not rep.ok:
            if rep.err.startswith("EPERM:denied"):
                raise PermissionError(rep.err)   # the _op contract
            raise RuntimeError(f"admin {cmd!r} on osd.{osd}: "
                               f"{rep.err}")
        return _json.loads(rep.blob)

    def mon_command(self, kind: str, timeout: float = 10.0):
        """Read-only monitor command (`ceph status` / `health` /
        `health detail` / `prometheus` / `perf dump` / `report dump`):
        hunts the monitors in order, answers from the first one's
        MgrReport aggregate. With cephx on, establishes mon sessions
        first (the commands need mon r)."""
        import json as _json
        self._ensure_mon_sessions()
        last = None
        for mon in self.c.mon_names():
            try:
                rep = self.mon_rpc.call(
                    mon, lambda rid: MMonCmd(rid, True, kind),
                    timeout=timeout)
            except (ConnectionError, KeyError, OSError) as e:
                last = str(e)
                continue             # hunt the next monitor
            if rep.ok:
                return _json.loads(rep.blob)
            if rep.err.startswith("EPERM"):
                raise PermissionError(rep.err)
            raise RuntimeError(f"mon command {kind!r}: {rep.err}")
        raise ConnectionError(f"no monitor answered {kind!r}: {last}")

    def status(self) -> dict:
        return self.mon_command("status")

    def health(self, detail: bool = False) -> dict:
        return self.mon_command("health detail" if detail
                                else "health")

    def prometheus_text(self) -> str:
        return self.mon_command("prometheus")["text"]

    def _op(self, kind: str, ps: int, body_fn, timeout=None,
            retries=30, retry_sleep=0.3, names=None) -> bytes:
        op = _WireOp(kind, ps, body_fn, names=names)
        self._run_ops([op], timeout=timeout, retries=retries,
                      retry_sleep=retry_sleep)
        return op.blob

    def _encode_op_body(self, op: "_WireOp") -> list:
        e = Encoder()
        e.u32(op.ps)
        op.body_fn(e)
        return e.segments()

    def _settle(self, op: "_WireOp", ok: bool, blob: bytes, err: str,
                tgt: str, need_auth: set) -> None:
        """Fold one reply (or batch sub-reply) into the op's retry
        state — the same decision table the sequential _op loop ran."""
        if ok:
            op.blob, op.done = blob, True
            return
        op.last = err
        if err == "EPERM:unauthenticated":
            # first contact with this daemon (or it restarted):
            # establish the cephx session and retry the op
            need_auth.add(tgt)
            return
        if err.startswith("EPERM:denied"):
            # caps refusal is deterministic; retrying is useless
            op.fatal = PermissionError(err)
            return
        if err.startswith("ClsError:"):
            # a class method REFUSED the op (EBUSY-style):
            # deterministic, retrying can't change the answer
            from .objclass import ClsError
            op.fatal = ClsError(err[9:])
            return
        if err.startswith("KeyError"):
            # no-such-object is deterministic at the primary that
            # answered: retry sleeps cannot make a deleted object
            # reappear
            op.fatal = KeyError(err[9:] or err)
            return
        if "failsafe full" in err:
            # r21: the OSD's local hard-stop. Park until a NEWER map
            # could have changed the picture (capacity-ladder commits
            # bump the epoch) — the op never burns retry budget and
            # never surfaces while parked (the RADOS full-wait
            # contract); a fresh epoch probes exactly once.
            op.full_wait = self.osdmap.epoch \
                if self.osdmap is not None else 0
            op.full_pin_t = time.monotonic()
            return
        # anything else is transport-shaped: retarget and retry
        if op.kind in self._HEDGE_KINDS \
                and ("peering" in err or "not primary" in err):
            # the mapped primary exists but cannot serve yet
            # (WaitUpThru / restore pending): route the next round
            # straight to a surviving shard as a degraded read
            # instead of sleeping out the peering window
            op.try_degraded = True

    #: kinds the FULL flags park — writes that ADD bytes; "remove"
    #: deliberately passes (freeing space is how a full cluster
    #: recovers — the implicit FULL_TRY every delete carries)
    _FULL_WAIT_KINDS = frozenset({"write", "write_at", "append"})

    #: longest a failsafe-bounced op parks without a newer map before
    #: probing again anyway — liveness for full windows too short for
    #: the ladder to ever commit (each probe costs one retry round, so
    #: a persistently-failsafe cluster still errors out eventually
    #: instead of wedging the client forever)
    _FAILSAFE_REPROBE_S = 2.0

    def _full_parked(self, op: "_WireOp") -> bool:
        """r21: does this op sit out the current dispatch round?
        True while (a) an OSD failsafe bounce pinned it to an epoch
        the cached map hasn't passed yet, or (b) the map flies the
        cluster FULL flag or the pool's quota-full flag (full_try
        clients push through the map flags, never the failsafe)."""
        m = self.osdmap
        if m is None:
            return False
        if op.full_wait is not None:
            if m.epoch > op.full_wait:
                op.full_wait = None    # newer map: probe again
            elif op.full_pin_t is not None and \
                    time.monotonic() - op.full_pin_t \
                    >= self._FAILSAFE_REPROBE_S:
                # stale-map liveness valve: a failsafe bounce whose
                # cause cleared before any MgrReport reached the mon
                # never produces a newer epoch — probe anyway after a
                # bounded park; a store still at failsafe just
                # re-bounces and re-pins (slow periodic probe)
                op.full_wait = None
            else:
                return True
        if op.kind not in self._FULL_WAIT_KINDS or self.full_try:
            return False
        return m.cluster_full or 1 in m.full_pools

    def _full_backoff(self, base_sleep: float) -> None:
        """One parked-write beat: re-probe the map from a monitor
        (flag clears arrive as ordinary map fan-out; the request
        covers a client the broadcast missed), then a jittered sleep.
        The whole interval lands in full_backoff_time — the telemetry
        plane discloses it instead of charging it to write latency."""
        import random as _random
        t0 = time.monotonic()
        with self._lock:
            epoch = self.osdmap.epoch if self.osdmap is not None else 0
        for mon in self.c.mon_names():
            try:
                self.msgr.send(mon, MOSDMapRequest(epoch))
                break
            except (KeyError, OSError, ConnectionError):
                continue
        time.sleep(base_sleep * (0.5 + _random.random()))
        self.perf.tinc("full_backoff_time", time.monotonic() - t0)

    # -- degraded / hedged read dispatch --------------------------------------

    def _hedge_delay_s(self) -> float | None:
        """Resolve the live hedge delay: constructor override, else
        the committed central config, else the schema default. > 0 =
        fixed seconds; None = hedging off; 0/auto derives from this
        client's OpTracker read-latency history (a generous multiple
        of recent p95, floored so healthy clusters almost never hedge
        and capped below the op timeout so a hedge still has time to
        win)."""
        raw = self.hedge_delay_ms
        if raw is None and self.osdmap is not None:
            raw = self.osdmap.config_kv.get("client_hedge_delay_ms")
        if raw is None:
            raw = 0.0
        try:
            raw = float(raw)
        except ValueError:
            return None
        if raw < 0:
            return None
        if raw > 0:
            return raw / 1e3
        hist = sorted(self.op_tracker.recent_durations(32))
        lo, hi = 0.15, max(0.15, self.c.op_timeout / 2.0)
        if not hist:
            return hi
        p95 = hist[min(len(hist) - 1, int(0.95 * len(hist)))]
        return min(max(4.0 * p95, lo), hi)

    # -- distributed tracing (r15): context stamping --------------------------

    def _trace_rate(self) -> float:
        """Live sample rate: constructor override, else the committed
        central config (client_trace_sample_rate), else the schema
        default. < 0 disables context stamping entirely (frames revert
        to the bit-identical v1 encoding)."""
        raw = self.trace_sample_rate
        if raw is None and self.osdmap is not None:
            raw = self.osdmap.config_kv.get("client_trace_sample_rate")
        if raw is None:
            raw = 0.01
        try:
            return float(raw)
        except (TypeError, ValueError):
            return 0.01

    def _make_trace_ctx(self, force: bool = False):
        """The context one op frame carries, or None when stamping is
        off. `force` (hedged/degraded dispatches) samples
        unconditionally — those are exactly the multi-hop latency
        stories the tracing plane exists for. A SAMPLED first hop also
        ships this client's per-target latency EWMAs + live complaint
        set, which the serving daemon folds into its repair-planner
        cost table (the r14 follow-up)."""
        from ..utils.flight_recorder import (TraceContext, coin,
                                             new_trace_id)
        rate = self._trace_rate()
        if rate < 0:
            return None
        sampled = force or coin(rate)
        lat = None
        suspects: tuple[int, ...] = ()
        if sampled:
            lat = {int(t[4:]): v for t, v in self._lat_ewma.items()
                   if t.startswith("osd.")}
            suspects = tuple(sorted(
                int(t[4:]) for t in self._tgt_suspect
                if t.startswith("osd.")))
            ctx = TraceContext(new_trace_id(), new_trace_id(), True,
                               client_lat=lat or None,
                               client_suspects=suspects)
            self.last_trace_id = ctx.trace_id
            return ctx
        # unsampled: the id still travels, so every daemon can
        # retroactively assemble this op if it turns out slow
        return TraceContext(new_trace_id(), new_trace_id(), False)

    def _flush_trace_spans(self, force: bool = False) -> None:
        """Ship this client's freshly finished spans — plus its
        CUMULATIVE observed-latency counters (r18: the true
        client-side half of observed_client_latency) — to the
        monitors (clients have no MgrReport heartbeat — they flush
        after op rounds, throttled)."""
        import json as _json
        now = time.monotonic()
        if not force and now - self._trace_flushed < 1.0:
            return
        perf = self.perf.dump()
        new_samples = (perf.get("op_lat") or {}).get("avgcount", 0) \
            != self._perf_shipped_count
        if not self.flight.pending_ship() and not new_samples:
            return
        self._trace_flushed = now
        self._perf_shipped_count = \
            (perf.get("op_lat") or {}).get("avgcount", 0)
        spans = self.flight.drain(512)
        blob = _json.dumps({"name": self.msgr.name, "kind": "trace",
                            "spans": spans, "client_perf": perf},
                           separators=(",", ":")).encode()
        for mon in self.c.mon_names():
            try:
                self.msgr.send(mon, MMgrReport(0, True, "trace", blob))
            except (KeyError, OSError, ConnectionError):
                pass

    #: link-cost feed cache TTL (seconds): the matrix only changes on
    #: the report cadence, so pulling faster buys nothing
    _LINK_COST_TTL = 5.0

    def _maybe_refresh_link_costs(self) -> None:
        """Kick ONE background pull of the mon link matrix when the
        cache aged out (r22). Never blocks the caller: a read-path
        consumer racing a dead monitor must not inherit the mon-hunt
        timeout — it uses the stale cache and the refresh lands for
        the next op."""
        now = time.monotonic()
        if now - self._link_costs_at < self._LINK_COST_TTL:
            return
        if not self._link_gate.acquire(blocking=False):
            return                  # a pull is already in flight

        def _pull():
            try:
                d = self.mon_command("dump_osd_network", timeout=5.0)
                costs: dict[int, int] = {}
                for row in d.get("links") or []:
                    cost = int(float(row.get("ewma_ms", 0.0)) * 1e3)
                    for end in (row.get("from"), row.get("to")):
                        if isinstance(end, str) \
                                and end.startswith("osd."):
                            try:
                                o = int(end[4:])
                            except ValueError:
                                continue
                            costs[o] = max(costs.get(o, 0), cost)
                self._link_costs = costs
                self.perf.inc("link_cost_refreshes")
            except Exception:   # noqa: BLE001 — no mon, no feed: the
                pass            # client's own EWMAs still order reads
            finally:
                # stamp AFTER the attempt (success or not): a dead
                # quorum retries at TTL cadence, not per read
                self._link_costs_at = time.monotonic()
                self._link_gate.release()

        threading.Thread(target=_pull, daemon=True).start()

    def _read_fallback(self, ps: int, avoid: set[str]) -> str | None:
        """Next-best acting shard for a degraded/hedged read: an
        acting member that is up in OUR map and not in `avoid`,
        preferring the one with the best recent latency — the WORSE of
        the client's own per-target EWMA and the mon link matrix's
        measured cost (r22), so a shard behind a degraded wire ranks
        down even when this client hasn't personally paid it yet —
        then acting order."""
        self._maybe_refresh_link_costs()
        acting = self.osdmap.pg_to_up_acting_osds(1, ps)[2]
        n = len(self.osdmap.osd_up)
        cands = []
        for rank, o in enumerate(dict.fromkeys(acting)):
            if not _valid_osd(o, n) or not self.osdmap.osd_up[o]:
                continue
            name = f"osd.{o}"
            if name in avoid or self._target_suspected(name):
                continue
            # unmeasured targets rank after measured ones, in acting
            # order — "next-best" prefers a shard we know answers
            # fast. The link feed COUNTS as measurement: it is a real
            # RTT some daemon paid, not a guess.
            own = self._lat_ewma.get(name)
            feed = self._link_costs.get(o)
            if own is None and feed is None:
                key = float("inf")
            elif own is None:
                key = feed / 1e6
            else:
                key = own if feed is None else max(own, feed / 1e6)
                if feed is not None and feed / 1e6 > own:
                    self.perf.inc("link_cost_demotions")
            cands.append((key, rank, name))
        if not cands:
            return None
        return min(cands)[2]

    def _note_latency(self, tgt: str, dt: float) -> None:
        prev = self._lat_ewma.get(tgt)
        self._lat_ewma[tgt] = dt if prev is None \
            else 0.75 * prev + 0.25 * dt
        # r18: the same sample feeds the mergeable client-observed
        # histogram (ships with trace flushes -> the monitors'
        # observed_client_latency feed)
        self.perf.tinc("op_lat", dt)
        self._tgt_suspect.pop(tgt, None)   # it answered: complaint over

    def _suspect_target(self, tgt: str) -> None:
        if self.osdmap is not None:
            self._tgt_suspect[tgt] = self.osdmap.epoch

    def _target_suspected(self, tgt: str) -> bool:
        epoch = self._tgt_suspect.get(tgt)
        if epoch is None:
            return False
        if self.osdmap is None or self.osdmap.epoch != epoch:
            # a newer map re-earns trust (the primary may have moved
            # or revived); one slow round trip re-proves it either way
            self._tgt_suspect.pop(tgt, None)
            return False
        return True

    def _submit_degraded(self, op: "_WireOp", tgt: str,
                         hints: set[str],
                         span_name: str = "client.op") -> _TracedCall:
        """One read re-issued as a `read_degraded` frame: names plus
        the osd ids being routed around (the server skips them in its
        meta gather and decode instead of re-paying their timeouts).
        Degraded/hedged dispatches are ALWAYS-SAMPLED trace origins
        (the multi-hop tail stories the tracing plane exists for)."""
        e = Encoder()
        e.u32(op.ps)
        e.list(op.names, Encoder.string)
        e.list(sorted(int(t[4:]) for t in hints
                      if t.startswith("osd.")),
               lambda en, v: en.i32(v))
        body = e.bytes()
        ctx = self._make_trace_ctx(force=True)
        pend = self.rpc.submit(
            tgt, lambda rid: MOSDOp(rid, True, "read_degraded", body,
                                    trace=ctx),
            nbytes=len(body))
        tags = {"tgt": tgt, "ops": len(op.names), "degraded": True}
        return _TracedCall(self, pend, ctx, span_name, tags)

    def _settle_degraded(self, op: "_WireOp", ok: bool, blob: bytes,
                         err: str, tgt: str, need_auth: set) -> None:
        """Fold a read_degraded reply: the server answers in readv
        encoding (list of blobs), so a single-name `read` op unwraps
        its one blob; `readv` ops pass through unchanged."""
        self._settle(op, ok, blob, err, tgt, need_auth)
        if op.done:
            if op.kind == "read":
                op.blob = Decoder(op.blob).list(Decoder.blob)[0]
            self.perf.inc("degraded_served")

    def _fold_frame_reply(self, tgt: str, group: list["_WireOp"], rep,
                          need_auth: set, skip=()) -> None:
        """Fold one primary-frame reply into its ops' retry state
        (the decision table of the sequential loop), skipping ops a
        hedge already settled."""
        if rep.ok and len(group) > 1:
            d = Decoder(rep.blob)
            subs = d.list(lambda dd: (dd.boolean(), dd.blob(),
                                      dd.string()))
            for op, (ok, blob, err) in zip(group, subs):
                if op not in skip:
                    self._settle(op, ok, blob, err, tgt, need_auth)
        elif rep.ok:
            if group[0] not in skip:
                self._settle(group[0], True, rep.blob, "", tgt,
                             need_auth)
        else:
            for op in group:
                if op not in skip:
                    self._settle(op, False, b"", rep.err, tgt,
                                 need_auth)

    def _await_hedged(self, tgt: str, group: list["_WireOp"],
                      pend: _PendingCall, t0: float, deadline: float,
                      hedge_at: float, need_auth: set) -> None:
        """First-complete-wins wait for one read frame: if the primary
        reply is not in by `hedge_at`, duplicate every still-open op
        to its next-best shard as a degraded read; whichever answer
        lands first settles each op, losers are cancelled (window slot
        freed, late reply dropped), and every handle retires exactly
        once."""
        hedges: list[tuple["_WireOp", str, _PendingCall]] = []
        if not pend.ready(max(0.0, hedge_at - time.monotonic())):
            for op in group:
                alt = self._read_fallback(op.ps, op.avoid | {tgt})
                if alt is None:
                    continue
                self.perf.inc("hedge_issued")
                hedges.append((op, alt, self._submit_degraded(
                    op, alt, op.avoid | {tgt},
                    span_name="client.hedge")))
        ev = threading.Event()
        pend.add_waiter(ev)
        for _op, _alt, hp in hedges:
            hp.add_waiter(ev)
        primary_open = True
        won_by_hedge: set[int] = set()   # id(op) settled by a hedge
        while True:
            progressed = False
            if primary_open and pend.ready(0.0):
                primary_open = False
                progressed = True
                try:
                    rep = pend.take()
                except (ConnectionError, KeyError, OSError) as err:
                    self._suspect_target(tgt)
                    for op in group:
                        if id(op) in won_by_hedge or op.done:
                            continue
                        op.last = str(err)
                        op.avoid.add(tgt)
                        op.try_degraded = True
                else:
                    self._note_latency(tgt, time.monotonic() - t0)
                    self._fold_frame_reply(
                        tgt, group, rep, need_auth,
                        skip={op for op in group
                              if id(op) in won_by_hedge})
            still = []
            for op, alt, hp in hedges:
                if op.done or op.fatal is not None:
                    # the primary settled it first: cancel the loser
                    hp.cancel()
                    self.perf.inc("hedge_losses")
                    progressed = True
                    continue
                if hp.ready(0.0):
                    progressed = True
                    try:
                        hrep = hp.take()
                    except (ConnectionError, KeyError, OSError) as err:
                        op.last = str(err)
                        op.avoid.add(alt)
                    else:
                        self._settle_degraded(op, hrep.ok, hrep.blob,
                                              hrep.err, alt, need_auth)
                        if op.done:
                            won_by_hedge.add(id(op))
                            self.perf.inc("hedge_wins")
                    continue
                still.append((op, alt, hp))
            hedges = still
            open_ops = any(not op.done and op.fatal is None
                           for op in group)
            if primary_open and not open_ops:
                # every op settled by hedges before the primary said a
                # word: cancel it AND remember the complaint — later
                # reads go straight degraded instead of re-paying the
                # hedge delay every op while this map epoch lasts
                pend.cancel()
                self.perf.inc("hedge_cancelled")
                self._suspect_target(tgt)
                primary_open = False
                progressed = True
            if not primary_open and not hedges:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if primary_open:
                    pend.cancel()
                    self.perf.inc("hedge_cancelled")
                    self._suspect_target(tgt)
                    for op in group:
                        if op.done or op.fatal is not None \
                                or id(op) in won_by_hedge:
                            continue
                        op.last = f"rpc to {tgt} timed out"
                        op.avoid.add(tgt)
                        op.try_degraded = True
                for op, alt, hp in hedges:
                    hp.cancel()
                    self.perf.inc("hedge_cancelled")
                    if not op.done and op.fatal is None:
                        op.last = f"hedge to {alt} timed out"
                return
            if not progressed:
                ev.wait(min(remaining, 0.05))
                ev.clear()

    def _run_ops(self, ops: list["_WireOp"], timeout=None,
                 retries=30, retry_sleep=0.3) -> None:
        """Pipelined dispatch of many ops: every round, outstanding
        ops are grouped by their CURRENT primary, ops sharing a
        primary coalesce into one `batch` frame, and all frames go
        out through the windowed rpc before any reply is awaited — so
        a client batch really has window-many ops on the wire (the
        Objecter's in-flight pipeline, ref: src/osdc/Objecter.cc
        op_submit + the objecter_inflight_ops window). Retry/error
        semantics per op are identical to the old one-op loop.

        Reads additionally get graceful degradation (the degraded-read
        fast path, ROADMAP item 3):
        * DEGRADED DISPATCH — a read whose primary is down in the map,
          parked in peering, or has already transport-failed goes
          straight to the next-best acting shard as a `read_degraded`
          frame instead of sleeping out detection + peering;
        * HEDGING — a read frame with no reply after the hedge delay
          (live via client_hedge_delay_ms; auto mode derives it from
          this client's OpTracker history) is duplicated to the
          next-best shard; the first complete answer wins and the
          loser is cancelled (slot freed, late reply dropped).
        Both paths ride the same windowed rpc, so in-flight accounting
        stays exactly-once per handle; mutations never hedge."""
        if timeout is None:
            timeout = self.c.op_timeout + 8.0   # server-side retry room
        rounds = 0
        while rounds < retries:
            outstanding = [op for op in ops
                           if not op.done and op.fatal is None]
            if not outstanding:
                break
            # r21 full-wait (ref: Objecter::_maybe_request_map +
            # the pool/cluster FULL pause): a mutating op parks —
            # undisplayed, unerrored, retry budget untouched — while
            # the cached map flies a FULL flag over its pool/cluster,
            # or while an OSD failsafe bounce pins it to the bounced
            # epoch. A round where EVERY outstanding op is parked
            # sleeps a jittered beat + re-probes the map instead of
            # dispatching; the ops resume exactly-once when a newer
            # epoch clears the gate.
            parked = [op for op in outstanding
                      if self._full_parked(op)]
            if parked and len(parked) == len(outstanding):
                self._full_backoff(retry_sleep)
                continue
            if parked:
                outstanding = [op for op in outstanding
                               if not self._full_parked(op)]
            rounds += 1
            hedge_s = self._hedge_delay_s()
            by_tgt: dict[str, list[_WireOp]] = {}
            deg_ops: list[tuple[_WireOp, str]] = []
            for op in outstanding:
                tgt = None
                try:
                    tgt = self._primary(op.ps)
                except ConnectionError as e:
                    op.last = str(e)   # no primary yet
                if op.kind in self._HEDGE_KINDS and op.names is not None \
                        and (tgt is None or op.try_degraded
                             or tgt in op.avoid
                             or self._target_suspected(tgt)):
                    alt = self._read_fallback(op.ps, op.avoid)
                    if alt is not None:
                        deg_ops.append((op, alt))
                        continue
                if tgt is None:
                    continue           # wait for a serviceable map
                by_tgt.setdefault(tgt, []).append(op)
            handles = []
            for tgt, group in by_tgt.items():
                # r15: every frame carries a trace context (the id
                # travels so slow ops assemble retroactively; the
                # sampled flag — probabilistic — gates eager span
                # recording at every hop). _TracedCall records the
                # client.op root span however the handle retires.
                ctx = self._make_trace_ctx()
                if len(group) == 1:
                    op = group[0]
                    body = self._encode_op_body(op)
                    nbytes = sum(len(s) for s in body)
                    pend = self.rpc.submit(
                        tgt, lambda rid, k=op.kind, b=body, tr=ctx:
                        MOSDOp(rid, True, k, b, trace=tr),
                        nbytes=nbytes)
                else:
                    # coalesce: one frame carries every outstanding op
                    # for this primary (small-op dispatch stops paying
                    # a round trip per PG)
                    e = Encoder()
                    e.u32(len(group))
                    for op in group:
                        e.string(op.kind)
                        e.blob_ref(self._encode_op_body(op))
                    body = e.segments()
                    nbytes = sum(len(s) for s in body)
                    pend = self.rpc.submit(
                        tgt, lambda rid, b=body, tr=ctx:
                        MOSDOp(rid, True, "batch", b, trace=tr),
                        nbytes=nbytes)
                pend = _TracedCall(self, pend, ctx, "client.op",
                                   {"tgt": tgt, "ops": len(group)})
                handles.append((tgt, group, pend, time.monotonic()))
            deg_handles = []
            for op, alt in deg_ops:
                self.perf.inc("degraded_dispatch")
                # the hint set carries every complained-about target
                # too, so the serving shard's meta gather skips the
                # dead primary instead of re-paying its timeout
                deg_handles.append((alt, op, self._submit_degraded(
                    op, alt, op.avoid | set(self._tgt_suspect))))
            need_auth: set[str] = set()
            for tgt, group, pend, t0 in handles:
                hedgeable = (
                    hedge_s is not None
                    and all(o.kind in self._HEDGE_KINDS
                            and o.names is not None for o in group))
                track = all(o.kind in self._HEDGE_KINDS
                            for o in group)
                frame_op = self.op_tracker.create_op(
                    f"client_read -> {tgt} x{len(group)}") \
                    if track else None
                if hedgeable:
                    self._await_hedged(tgt, group, pend, t0,
                                       t0 + timeout, t0 + hedge_s,
                                       need_auth)
                    if frame_op is not None:
                        frame_op.finish()
                    continue
                try:
                    rep = pend.wait(timeout)
                except (ConnectionError, KeyError, OSError) as err:
                    self._suspect_target(tgt)
                    for op in group:
                        op.last = str(err)
                        op.avoid.add(tgt)
                        if op.kind in self._HEDGE_KINDS:
                            op.try_degraded = True
                    continue
                finally:
                    if frame_op is not None:
                        frame_op.finish()
                self._note_latency(tgt, time.monotonic() - t0)
                self._fold_frame_reply(tgt, group, rep, need_auth)
            for alt, op, pend in deg_handles:
                try:
                    rep = pend.wait(timeout)
                except (ConnectionError, KeyError, OSError) as err:
                    op.last = str(err)
                    op.avoid.add(alt)
                    continue
                self._settle_degraded(op, rep.ok, rep.blob, rep.err,
                                      alt, need_auth)
            for tgt in need_auth:
                try:
                    self._authorize(tgt)
                except PermissionError:
                    raise          # caps refusal is deterministic
                except (ConnectionError, KeyError, OSError):
                    pass   # daemon unreachable: the round retries
            remaining = [op for op in ops
                         if not op.done and op.fatal is None]
            if not remaining:
                break
            if not need_auth:
                time.sleep(retry_sleep)   # map may be in flight
        self._flush_trace_spans()
        for op in ops:
            if op.fatal is not None and not isinstance(op.fatal,
                                                       KeyError):
                raise op.fatal
        for op in ops:
            if op.fatal is not None:
                raise op.fatal
        for op in ops:
            if not op.done:
                raise ConnectionError(
                    f"op {op.kind} pg 1.{op.ps} failed: {op.last}")

    def _snapc(self) -> int:
        """The client's snap context (ref: MOSDOp SnapContext): every
        mutating op carries the newest snap_seq this client has seen
        so a map-lagging primary refuses rather than skipping COW."""
        return self.osdmap.pools[1].snap_seq

    def write(self, objects: dict[str, bytes]) -> None:
        by_pg: dict[int, dict[str, bytes]] = {}
        for name, data in objects.items():
            ps = self.osdmap.object_to_pg(1, name)[1]
            by_pg.setdefault(ps, {})[name] = bytes(data)
        # one op per PG, ALL pipelined through the window (and ops
        # landing on the same primary coalesce into one frame); the
        # data blobs ride by reference from here to sendmsg
        self._run_ops([
            _WireOp("write", ps,
                    lambda e, g=group: e.u64(self._snapc()).mapping(
                        g, Encoder.string, Encoder.blob_ref))
            for ps, group in by_pg.items()])

    def write_at(self, name: str, offset: int, data: bytes) -> None:
        """Partial overwrite (the rados write-at-offset role): the
        primary serves it through the parity-delta RMW fast path when
        the stripe is clean — only the touched data shard(s) + m
        parity shards move on its fan-out — laddering to the
        full-stripe RMW otherwise."""
        ps = self.osdmap.object_to_pg(1, name)[1]
        self._op("write_at", ps,
                 lambda e: e.u64(self._snapc()).list(
                     [(name, int(offset), bytes(data))],
                     lambda en, t: en.string(t[0]).u64(t[1])
                     .blob_ref(t[2])))

    def append(self, name: str, data: bytes) -> None:
        """Append to a stream object: lands at the primary-known tail
        (the append-optimized layout — successive appends ride the
        RMW fast path with no pre-image read at all)."""
        ps = self.osdmap.object_to_pg(1, name)[1]
        self._op("append", ps,
                 lambda e: e.u64(self._snapc()).list(
                     [(name, 0, bytes(data))],
                     lambda en, t: en.string(t[0]).u64(t[1])
                     .blob_ref(t[2])))

    def read(self, name: str) -> bytes:
        ps = self.osdmap.object_to_pg(1, name)[1]
        return self._op("read", ps,
                        lambda e: e.string(name), names=[name])

    def read_many(self, names) -> dict[str, bytes]:
        """Batched reads: ONE multi-name op per PG (the daemon decodes
        the whole group in one batched launch), all PG ops pipelined
        through the window with per-primary coalescing (the librados
        aio_read batch role). Raises KeyError if any name is absent."""
        names = list(names)
        by_pg: dict[int, list[str]] = {}
        for name in names:
            ps = self.osdmap.object_to_pg(1, name)[1]
            by_pg.setdefault(ps, []).append(name)
        ops = {ps: _WireOp("readv", ps,
                           lambda e, g=group: e.list(g, Encoder.string),
                           names=group)
               for ps, group in by_pg.items()}
        self._run_ops(list(ops.values()))
        out: dict[str, bytes] = {}
        for ps, group in by_pg.items():
            blobs = Decoder(ops[ps].blob).list(Decoder.blob)
            out.update(zip(group, blobs))
        return {n: out[n] for n in names}

    def remove(self, names) -> None:
        """Delete objects (a LOGGED mutation: a shard down across
        the delete replays it on rejoin instead of resurrecting a
        stale copy — the pg_log_entry_t DELETE semantics the backend
        already enforces)."""
        names = [names] if isinstance(names, str) else list(names)
        by_pg: dict[int, list[str]] = {}
        for name in names:
            ps = self.osdmap.object_to_pg(1, name)[1]
            by_pg.setdefault(ps, []).append(name)
        self._run_ops([
            _WireOp("remove", ps,
                    lambda e, g=group: e.u64(self._snapc()).list(
                        g, Encoder.string))
            for ps, group in by_pg.items()])

    # -- pool snapshots over the wire ----------------------------------------

    def _mon_cast(self, msg: Message) -> None:
        """Broadcast to every monitor (queue-everywhere: whoever leads
        proposes; idempotent mutations commit exactly once)."""
        for mon in self.c.mon_names():
            try:
                self.msgr.send(mon, msg)
            except (KeyError, OSError, ConnectionError):
                pass

    def _pool_op(self, kind: str, snap: str) -> None:
        self._ensure_mon_sessions()
        self._mon_cast(MPoolOp(kind, snap))

    def snap_create(self, name: str, timeout: float = 15.0) -> int:
        """Named pool snapshot: monitor-quorum-committed (the snap
        rides pg_pool_t in the OSDMap), observed via this client's
        map subscription. Returns the snap id."""
        self._pool_op("mksnap", name)
        self.c._wait(
            lambda: self.osdmap is not None
            and name in self.osdmap.pools[1].snaps.values(),
            timeout, f"snap {name!r} committed")
        return next(s for s, n in self.osdmap.pools[1].snaps.items()
                    if n == name)

    def snap_remove(self, name: str, timeout: float = 15.0) -> None:
        self._pool_op("rmsnap", name)
        self.c._wait(
            lambda: self.osdmap is not None
            and name not in self.osdmap.pools[1].snaps.values(),
            timeout, f"snap {name!r} removed")

    def snap_read(self, name: str, sid: int) -> bytes:
        ps = self.osdmap.object_to_pg(1, name)[1]
        return self._op("snap_read", ps,
                        lambda e: e.string(name).u64(sid), retries=6)

    def snap_rollback(self, name: str, sid: int) -> None:
        ps = self.osdmap.object_to_pg(1, name)[1]
        self._op("rollback", ps,
                 lambda e: e.u64(self._snapc()).string(name).u64(sid),
                 retries=6)

    # -- osd administration over the wire ------------------------------------

    def osd_out(self, osd: int, timeout: float = 15.0) -> None:
        """`ceph osd out N`: weight to 0, committed through quorum;
        CRUSH steers the OSD's slots elsewhere and backfill follows."""
        self._ensure_mon_sessions()
        self._mon_cast(MOsdAdmin("out", osd))
        self.c._wait(
            lambda: self.osdmap is not None
            and self.osdmap.osd_weight[osd] == 0,
            timeout, f"osd.{osd} marked out")

    def osd_in(self, osd: int, weight: float = 1.0,
               timeout: float = 15.0) -> None:
        self._ensure_mon_sessions()
        self._mon_cast(MOsdAdmin("in", osd, weight))
        self.c._wait(
            lambda: self.osdmap is not None
            and self.osdmap.osd_weight[osd] > 0,
            timeout, f"osd.{osd} marked in")

    def osd_reweight(self, osd: int, weight: float,
                     timeout: float = 15.0) -> None:
        self._ensure_mon_sessions()
        self._mon_cast(MOsdAdmin("reweight", osd, weight))
        want = int(weight * 0x10000)
        self.c._wait(
            lambda: self.osdmap is not None
            and self.osdmap.osd_weight[osd] == want,
            timeout, f"osd.{osd} reweighted")

    def pool_set_quota(self, max_bytes: int = 0, max_objects: int = 0,
                       pool_id: int = 1,
                       timeout: float = 15.0) -> None:
        """`ceph osd pool set-quota` (r21) — quorum-committed onto the
        map; 0 clears that bound. POOL_FULL raises/clears on the
        leader's next capacity tick (it needs the MgrReport pool
        aggregates, so the flag follows the quota by up to a beat)."""
        self._ensure_mon_sessions()
        self._mon_cast(MPoolQuotaOp(pool_id, int(max_bytes),
                                    int(max_objects)))
        self.c._wait(
            lambda: self.osdmap is not None
            and pool_id in self.osdmap.pools
            and self.osdmap.pools[pool_id].quota_max_bytes
            == int(max_bytes)
            and self.osdmap.pools[pool_id].quota_max_objects
            == int(max_objects),
            timeout, f"pool {pool_id} quota committed")

    # -- centralized config over the wire ------------------------------------

    def config_set(self, key: str, value, timeout: float = 15.0) -> None:
        """`ceph config set` — quorum-committed, observed through the
        map subscription (ref: ConfigMonitor::prepare_command)."""
        value = str(value)
        self._ensure_mon_sessions()
        self._mon_cast(MConfigOp("set", key, value))
        self.c._wait(
            lambda: self.osdmap is not None
            and self.osdmap.config_kv.get(key) == value,
            timeout, f"config {key}={value!r} committed")

    def config_rm(self, key: str, timeout: float = 15.0) -> None:
        self._ensure_mon_sessions()
        self._mon_cast(MConfigOp("rm", key))
        self.c._wait(
            lambda: self.osdmap is not None
            and key not in self.osdmap.config_kv,
            timeout, f"config {key} removed")

    def config_get(self, key: str) -> str | None:
        """The committed central value (None = not centrally set)."""
        if self.osdmap is None:
            return None
        return self.osdmap.config_kv.get(key)

    # -- scrub / repair / object classes over the wire -----------------------

    def deep_scrub(self, ps: int) -> dict:
        import json as _json
        return _json.loads(self._op("deep_scrub", ps, lambda e: None))

    def repair_pg(self, ps: int) -> dict:
        import json as _json
        return _json.loads(self._op("repair", ps, lambda e: None))

    def cls_exec(self, name: str, cls: str, method: str,
                 inp: bytes = b"") -> bytes:
        ps = self.osdmap.object_to_pg(1, name)[1]
        return self._op("cls", ps,
                        lambda e: e.u64(self._snapc()).string(name)
                        .string(cls).string(method).blob(inp))

    def shutdown(self) -> None:
        self.msgr.shutdown()


class StandaloneCluster:
    """Orchestrates the endpoints; the qa/standalone helpers' role."""

    def __init__(self, n_osds: int = 6,
                 profile: str = "plugin=tpu_rs k=2 m=1 impl=bitlinear",
                 pg_num: int = 4, store: str = "mem",
                 store_dir: str | None = None,
                 secret: bytes | None = None,
                 compress: str | None = None, cephx: bool = False,
                 hb_interval: float = 0.25, hb_grace: float = 1.2,
                 min_reporters: int = 2, op_timeout: float = 8.0,
                 chunk_size: int = 256, verbose: bool | None = None,
                 op_window: int = 8, admin_dir: str | None = None,
                 op_shards: int = 1, msgr_workers: int = 1,
                 osd_procs: bool = False, msgr_uds: bool = True,
                 store_capacity: int = 0):
        import os as _os
        if verbose is None:
            verbose = bool(_os.environ.get("STANDALONE_VERBOSE"))
        from ..crush.map import Tunables, build_hierarchy, ec_rule, \
            replicated_rule
        from ..ec.interface import profile_from_string
        from ..ec.registry import factory
        self.secret = secret
        self.compress = compress
        # cephx realm (ref: AuthMonitor bootstrap + client.admin
        # keyring): entity secrets + rotating service secrets live in
        # one KeyServer every monitor serves from
        self.key_server = None
        self.admin_secret = None
        if cephx:
            from ..auth import KeyServer
            ks = KeyServer()
            self.key_server = ks
            self.admin_secret = ks.create_entity(
                "client.admin",
                caps={"mon": "allow *", "osd": "allow rwx"})
            ks.current_secret("auth")
            ks.current_secret("osd")
            ks.current_secret("mon")
            # every OSD daemon is itself a cephx principal (ref: the
            # osd.N keyring bootstrap-osd creates): shard fan-out and
            # peer meta reads authorize with osd service tickets
            self.osd_secrets = {
                o: ks.create_entity(f"osd.{o}",
                                    caps={"mon": "allow rw",
                                          "osd": "allow rwx"})
                for o in range(n_osds)}
        self.hb_interval, self.hb_grace = hb_interval, hb_grace
        self.min_reporters = min_reporters
        self.op_timeout = op_timeout
        # concurrency shape (r13): op-queue shards per OSD daemon
        # (osd_op_num_shards) + epoll reactor threads per messenger
        self.op_shards = max(1, int(op_shards))
        self.msgr_workers = max(1, int(msgr_workers))
        # Unix-domain messenger sockets by default: same frames and
        # handshake, ~2.5x the loopback-TCP bulk throughput on this
        # kernel (the whole harness is single-host by construction)
        self.msgr_uds = bool(msgr_uds)
        # client-side in-flight op window (ops; see Client/_Rpc —
        # 0 disables pipelining, restoring one-op-per-round-trip)
        self.op_window = op_window
        self.chunk_size = chunk_size
        self.verbose = verbose
        self.profile = profile
        toks = profile.split()
        self.is_erasure = toks[0] != "replicated"
        crush = build_hierarchy(n_osds, osds_per_host=1,
                                hosts_per_rack=max(4, n_osds))
        crush.tunables = Tunables(choose_total_tries=51)
        if self.is_erasure:
            coder = factory(profile)
            self.pool_size = coder.get_chunk_count()
            self.pool_min_size = coder.get_data_chunk_count()
            ec_rule(crush, 1, choose_type=1)
        else:
            prof = profile_from_string(" ".join(toks[1:]))
            self.pool_size = int(prof.get("size", 3))
            self.pool_min_size = int(prof.get(
                "min_size", self.pool_size - self.pool_size // 2))
            replicated_rule(crush, 1, choose_type=1, firstn=True)
        osdmap = OSDMap(crush)
        osdmap.add_pool(PGPool(1, pg_num=pg_num, size=self.pool_size,
                               min_size=self.pool_min_size,
                               crush_rule=1,
                               is_erasure=self.is_erasure))
        self.pg_num = pg_num
        self.n_osds = n_osds
        self.store_kind = store
        # r21: per-OSD byte budget (0 = unbounded — statfs reports
        # total 0 and the mon ladder never computes a ratio); the
        # osd_store_capacity_bytes config role for the harness tier
        self.store_capacity = int(store_capacity)
        self.store_dir = store_dir
        if store == "tin" and store_dir is None:
            import tempfile
            self.store_dir = tempfile.mkdtemp(prefix="standalone-tin-")
        # the run dir for daemon admin sockets (the /var/run/ceph
        # role): every daemon binds <dir>/<name>.asok. Kept short —
        # AF_UNIX paths cap at ~107 bytes.
        if admin_dir is None:
            import tempfile
            admin_dir = tempfile.mkdtemp(prefix="ceph-asok-")
        self.admin_dir = admin_dir
        self.mons = [MonDaemon(r, self) for r in range(3)]
        self.mons[0].osdmap = osdmap
        for m in self.mons[1:]:
            m.osdmap = OSDMap.decode(osdmap.encode())
        # multi-process OSDs (r13): each daemon in its own OS process
        # — the only way N daemons use N cores under the GIL. Spawn
        # all children first (imports overlap), then collect ready.
        self.osd_procs = bool(osd_procs)
        if self.osd_procs:
            from .multiproc import OSDProcHandle
            self.osds = {o: OSDProcHandle(self, o)
                         for o in range(n_osds)}
            for h in self.osds.values():
                h.wait_ready()
        else:
            self.osds = {o: OSDDaemon(o, self) for o in range(n_osds)}
        self.clients: list[Client] = []
        self._wire_peers()
        # initial map fan-out (the boot subscription)
        self.mons[0]._broadcast(osdmap.epoch)
        if self.osd_procs:
            # children's RAM is unreachable: poll their admin sockets
            # for a folded epoch instead of reading d.osdmap
            from ..utils.admin_socket import AdminSocketError

            def _fanned_out() -> bool:
                for h in self.osds.values():
                    try:
                        if h.asok("status",
                                  timeout=5.0)["osdmap_epoch"] < 1:
                            return False
                    except (OSError, AdminSocketError, ValueError):
                        return False
                return True
            self._wait(_fanned_out, 60, "initial map fan-out")
        else:
            self._wait(lambda: all(d.osdmap is not None
                                   for d in self.osds.values()), 10,
                       "initial map fan-out")

    # -- topology ------------------------------------------------------------

    def log(self, msg: str) -> None:
        # every cluster event also lands in the gathered log ring, so
        # `ceph daemon <name> log dump` reconstructs the timeline
        from ..utils.log import dout
        dout("osd", 4, f"standalone: {msg}")
        if self.verbose:
            print(f"standalone: {msg}", flush=True)

    def asok_path(self, name: str) -> str:
        import os as _os
        return _os.path.join(self.admin_dir, f"{name}.asok")

    def osd_ids(self) -> list[int]:
        return list(self.osds)

    def mon_names(self) -> list[str]:
        return [m.name for m in self.mons if not m._stop.is_set()]

    def map_subscribers(self) -> list[str]:
        subs = [d.name for d in self.osds.values()
                if not d._stop.is_set()]
        subs += [c.msgr.name for c in self.clients]
        return subs

    def make_store(self, osd_id: int):
        if self.store_kind == "tin":
            import os
            from .tinstore import TinStore
            # small cache on purpose: wire-tier datasets outgrow it,
            # proving the device-read path under real traffic
            return TinStore(os.path.join(self.store_dir,
                                         f"osd.{osd_id}"),
                            verify_reads=False,
                            cache_bytes=64 << 10,
                            capacity_bytes=self.store_capacity)
        return MemStore(capacity_bytes=self.store_capacity)

    def _wire_peers(self) -> None:
        every = ([(d.name, d.msgr) for d in self.osds.values()]
                 + [(m.name, m.msgr) for m in self.mons]
                 + [(c.msgr.name, c.msgr) for c in self.clients])
        for name_a, msgr_a in every:
            for name_b, msgr_b in every:
                if name_a != name_b:
                    msgr_a.add_peer(name_b, msgr_b.addr)

    def client(self, entity: str = "client.admin",
               secret: bytes | None = None,
               hedge_delay_ms: float | None = None,
               trace_sample_rate: float | None = None) -> Client:
        cl = Client(self, f"client.{len(self.clients)}",
                    entity=entity, secret=secret,
                    hedge_delay_ms=hedge_delay_ms,
                    trace_sample_rate=trace_sample_rate)
        self.clients.append(cl)
        self._wire_peers()
        # subscribe: any mon will answer with the current map
        self.mons[0]._broadcast(self.mons[0].osdmap.epoch)
        self._wait(lambda: cl.osdmap is not None, 10, "client map")
        return cl

    # -- cephx administration -------------------------------------------------

    def create_entity(self, name: str,
                      caps: dict[str, str]) -> bytes:
        """`ceph auth get-or-create` role: mint an entity keyring."""
        return self.key_server.create_entity(name, caps)

    def rotate_service_secrets(self, service: str = "osd") -> None:
        """Rotate + push to live daemons (ref: the monitor's periodic
        rotating-secret refresh daemons pick up via MAuth). Old
        tickets stay valid through the keep-window; beyond it daemons
        answer 'rotated out' and clients re-fetch."""
        self.key_server.rotate(service)
        rot = self.key_server.export_rotating(service)
        daemons = list(self.osds.values()) if service == "osd" \
            else self.mons if service == "mon" else []
        for d in daemons:
            if d._stop.is_set():
                continue
            if getattr(d, "verifier", None) is not None:
                d.verifier.refresh(rot)
            elif hasattr(d, "push_rotating"):
                # multi-process OSD (r15 parity): the rotated secrets
                # cross the child's control pipe — stdin, never argv —
                # and refresh the child's in-RAM verifier, exactly the
                # push an in-process daemon gets
                d.push_rotating(service, rot)

    # -- fault injection ------------------------------------------------------

    def kill_osd(self, osd: int) -> None:
        self.log(f"SIGKILL osd.{osd}")
        self.osds[osd].kill()

    def revive_osd(self, osd: int) -> None:
        self.log(f"revive osd.{osd}")
        fresh = self.osds[osd].revive()
        self.osds[osd] = fresh
        self._wire_peers()   # registers fresh's new address everywhere
        if self.osd_procs:
            fresh.boot()     # the child announces itself to the mons
            return
        for mon_name in self.mon_names():
            try:
                fresh.msgr.send(mon_name, MOSDBoot(osd))
            except (KeyError, OSError, ConnectionError):
                pass

    def _endpoints(self) -> list:
        eps = [(m.name, m.msgr) for m in self.mons
               if not m._stop.is_set()]
        eps += [(d.name, d.msgr) for d in self.osds.values()
                if not d._stop.is_set()]
        eps += [(c.msgr.name, c.msgr) for c in self.clients]
        return eps

    def inject_socket_failures(self, every: int, osds=None,
                               seed: int | None = None) -> None:
        """Enable ms_inject_socket_failures on the given OSD daemons
        (default: all alive): every Nth send tears the live socket
        down first, so the whole data+control plane runs through
        reconnect+replay continuously. 0 disables. `seed` resets each
        daemon's injection RNG/counters deterministically (per-daemon
        derived seeds) so a logged thrash seed replays the same
        teardown schedule."""
        targets = osds if osds is not None else list(self.osds)
        for o in targets:
            d = self.osds[o]
            if not d._stop.is_set():
                if seed is not None:
                    d.msgr.seed_injection(seed * 131 + o)
                d.msgr.set_inject_socket_failures(every)

    def inject_delays(self, every: int, max_ms: float, osds=None,
                      seed: int | None = None) -> None:
        """Enable ms_inject_delay on the given OSD daemons (default:
        all alive): uniform [0, max_ms] sleep before every Nth
        transmit. `seed` makes the per-daemon delay draws
        deterministic (see inject_socket_failures)."""
        targets = osds if osds is not None else list(self.osds)
        for o in targets:
            d = self.osds[o]
            if not d._stop.is_set():
                if seed is not None:
                    d.msgr.seed_injection(seed * 131 + o)
                d.msgr.set_inject_delay(every, max_ms)

    def link_degrade(self, from_osd: int, to_osd: int,
                     delay_ms: float, jitter_ms: float = 0.0,
                     seed: int | None = None) -> None:
        """r22: degrade the DIRECTED link osd.from→osd.to — every
        non-reactor transmit from_osd makes toward to_osd sleeps
        delay_ms plus uniform [0, jitter_ms] first (heartbeat pings
        included; the pong crosses back undelayed). delay_ms <= 0
        heals this link. `seed` re-seeds the sender's injection RNG
        so the jitter schedule replays (same derivation as
        inject_socket_failures)."""
        d = self.osds[from_osd]
        if d._stop.is_set():
            return
        self.log(f"link_degrade: osd.{from_osd} -> osd.{to_osd} "
                 f"+{delay_ms}ms jitter {jitter_ms}ms")
        if seed is not None:
            d.msgr.seed_injection(seed * 131 + from_osd)
        d.msgr.set_link_delay(f"osd.{to_osd}", delay_ms, jitter_ms)

    def heal_link_degrades(self) -> None:
        """Clear every injected link degrade, every endpoint."""
        self.log("link_degrade: healed")
        for _, msgr in self._endpoints():
            msgr.clear_link_delays()

    def partition(self, *groups) -> None:
        """Install a network partition (the partition-injection
        role, SURVEY §4): endpoints named in different groups cannot
        exchange frames — enforced at BOTH ends of every cross-group
        pair. Endpoints in no group stay fully connected (so a
        mon-only split leaves OSD traffic alone, like a switch fault
        between the mon racks)."""
        sets = [set(g) for g in groups]
        named = set().union(*sets) if sets else set()
        self.log(f"partition: {[sorted(s) for s in sets]}")
        for name, msgr in self._endpoints():
            mine = next((s for s in sets if name in s), None)
            msgr.set_blocked(named - mine if mine is not None
                             else set())

    def heal_partition(self) -> None:
        """Remove every injected block; queued frames replay."""
        self.log("partition: healed")
        for _, msgr in self._endpoints():
            msgr.set_blocked(set())

    def kill_mon(self, rank: int) -> None:
        """SIGKILL a monitor; the quorum machinery and leadership
        election carry on without it (2 of 3 still commit)."""
        self.log(f"SIGKILL mon.{rank}")
        self.mons[rank].kill()

    # -- monitor membership (`ceph mon add/remove`) ---------------------------

    def add_mon(self, timeout: float = 20.0) -> int:
        """Grow the quorum: boot a new monitor, store-sync it, commit
        its membership through the OLD quorum (ref: `ceph mon add` +
        MonmapMonitor::prepare_join). Returns the new rank."""
        rank = len(self.mons)
        self.log(f"add mon.{rank}")
        fresh = MonDaemon(rank, self)
        self.mons.append(fresh)
        self._wire_peers()
        for mon in self.mons:
            if mon is not fresh and not mon._stop.is_set():
                try:
                    fresh.msgr.send(mon.name, MMonSyncReq(0))
                except (KeyError, OSError, ConnectionError):
                    pass
        self._wait(lambda: fresh.osdmap is not None, timeout,
                   f"mon.{rank} bootstrap sync")
        self._cast_mon_join(MMonJoin(rank, True))
        self._wait(
            lambda: any(not m._stop.is_set() and m.osdmap is not None
                        and rank in m.osdmap.mon_members
                        for m in self.mons), timeout,
            f"mon.{rank} membership committed")
        return rank

    def _cast_mon_join(self, msg: MMonJoin) -> None:
        """Deliver a membership change to EVERY live monitor. A
        messenger has no loopback (a daemon is never its own peer),
        so a single-source broadcast can't reach the sender itself —
        fatal when the current LEADER is the one being removed (only
        the leader proposes). All live pairs cross-send instead, so
        each monitor, leader included, hears it from someone."""
        live = [m for m in self.mons if not m._stop.is_set()]
        for src in live:
            for dst in live:
                if src is dst:
                    continue
                try:
                    src.msgr.send(dst.name, msg)
                except (KeyError, OSError, ConnectionError):
                    pass

    def remove_mon(self, rank: int, timeout: float = 20.0) -> None:
        """Shrink the quorum: commit the member's departure through
        the current quorum, then stop its process (ref: `ceph mon
        remove`). A removed-but-running monitor can no longer lead or
        vote — membership rides the committed map."""
        self.log(f"remove mon.{rank}")
        target = self.mons[rank]
        self._cast_mon_join(MMonJoin(rank, False))
        self._wait(
            lambda: any(not m._stop.is_set() and m.osdmap is not None
                        and rank not in m.osdmap.mon_members
                        for m in self.mons), timeout,
            f"mon.{rank} removal committed")
        target.kill()

    def revive_mon(self, rank: int) -> None:
        """Restart a monitor: fresh endpoint, DURABLE Paxos state.
        The reference mon's acceptor state (promised pn, accepted-but-
        uncommitted value) and committed map live in its on-disk store
        and survive a restart — modeled here by carrying them from the
        killed daemon. Forgetting an acceptance would let two bodies
        commit for one epoch: the accept quorum that committed X must
        still REMEMBER X when a later collect quorum intersects it.
        A store sync from surviving peers then catches the committed
        map up BEFORE it may lead."""
        self.log(f"revive mon.{rank}")
        old = self.mons[rank]
        peers_epoch = max(
            (m.osdmap.epoch for m in self.mons
             if m is not old and not m._stop.is_set()
             and m.osdmap is not None), default=0)
        fresh = MonDaemon(rank, self, osdmap=old.osdmap)
        fresh._promised = old._promised
        fresh._accepted = old._accepted
        fresh._pn_seen = old._pn_seen
        self.mons[rank] = fresh
        self._wire_peers()
        for mon in self.mons:
            if mon is not fresh and not mon._stop.is_set():
                try:
                    fresh.msgr.send(mon.name, MMonSyncReq(0))
                except (KeyError, OSError, ConnectionError):
                    pass
        # wait for the sync to land (peers answer with their committed
        # map); if no peer is alive there is no quorum anyway and the
        # revived mon stays where its own store left it
        if any(not m._stop.is_set() for m in self.mons
               if m is not fresh):
            self._wait(lambda: fresh.osdmap is not None
                       and fresh.osdmap.epoch >= peers_epoch, 10,
                       f"mon.{rank} store sync")
        del old

    # -- barriers -------------------------------------------------------------

    def _wait(self, pred, timeout: float, what: str) -> None:
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            if pred():
                return
            time.sleep(0.05)
        import os as _os
        if _os.environ.get("STANDALONE_DEBUG"):
            import faulthandler
            import sys as _sys
            print(f"=== STANDALONE_DEBUG: '{what}' timed out; "
                  f"all thread stacks:", file=_sys.stderr, flush=True)
            faulthandler.dump_traceback(file=_sys.stderr)
        raise TimeoutError(f"standalone: {what} not reached "
                           f"in {timeout}s")

    def wait_for_down(self, osd: int, timeout: float = 30.0) -> None:
        """Emergent failure detection: pings miss -> reports -> quorum
        commit -> everyone's map shows the OSD down. The default
        budget allows for a loaded host (thread starvation stretches
        every stage; the suite flaked at 15s under full-suite load
        while passing x3 idle)."""
        if self.osd_procs:
            from ..utils.admin_socket import AdminSocketError

            def _down_everywhere() -> bool:
                # the committed map must mark it down AND every live
                # child must have folded an epoch at least that new
                epochs = [m.osdmap.epoch for m in self.mons
                          if not m._stop.is_set()
                          and m.osdmap is not None
                          and not m.osdmap.osd_up[osd]]
                if not epochs:
                    return False
                want = min(epochs)
                for h in self.osds.values():
                    if h._stop.is_set():
                        continue
                    try:
                        if h.asok("status",
                                  timeout=5.0)["osdmap_epoch"] < want:
                            return False
                    except (OSError, AdminSocketError, ValueError):
                        return False
                return True
            self._wait(_down_everywhere, timeout,
                       f"osd.{osd} marked down everywhere")
            return
        self._wait(
            lambda: all(d.osdmap is not None
                        and not d.osdmap.osd_up[osd]
                        for d in self.osds.values()
                        if not d._stop.is_set()),
            timeout, f"osd.{osd} marked down everywhere")

    def wait_for_clean(self, timeout: float = 30.0) -> None:
        """Every PG's primary hosts a backend whose acting set matches
        the map and whose shards are all caught up. In multi-process
        mode the parent cannot reach into a child's RAM: it reads the
        committed map from its in-process monitors and polls each
        primary child's `pg clean` over the admin socket."""
        if self.osd_procs:
            self._wait(self._proc_clean, timeout, "all PGs clean")
            return

        def clean() -> bool:
            for ps in range(self.pg_num):
                owner = None
                for d in self.osds.values():
                    if d._stop.is_set() or d.osdmap is None:
                        continue
                    acting = d.osdmap.pg_to_up_acting_osds(1, ps)[2]
                    if acting and acting[0] == d.osd_id:
                        owner = d
                        break
                if owner is None:
                    return False
                be = owner.backends.get(ps)
                if be is None or be.acting != acting:
                    return False
                if ps in owner._recovering:
                    return False   # async rebuild still in flight
            return True
        self._wait(clean, timeout, "all PGs clean")

    def _proc_clean(self) -> bool:
        from ..utils.admin_socket import AdminSocketError
        osdmap = next((m.osdmap for m in self.mons
                       if not m._stop.is_set()
                       and m.osdmap is not None), None)
        if osdmap is None:
            return False
        claims: dict[str, bool] = {}
        for h in self.osds.values():
            if h._stop.is_set():
                continue
            try:
                claims.update(h.asok("pg clean", timeout=5.0))
            except (OSError, AdminSocketError, ValueError):
                return False
        for ps in range(self.pg_num):
            acting = osdmap.pg_to_up_acting_osds(1, ps)[2]
            if not acting or not _valid_osd(acting[0],
                                            len(osdmap.osd_up)):
                return False
            if not claims.get(f"1.{ps}", False):
                return False
        return True

    def shutdown(self) -> None:
        for cl in self.clients:
            cl.shutdown()
        for d in self.osds.values():
            if not d._stop.is_set():
                d.kill()
        for m in self.mons:
            m.kill()
        import shutil
        shutil.rmtree(self.admin_dir, ignore_errors=True)
